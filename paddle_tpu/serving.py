"""Deployable serving artifacts — "train here, serve anywhere".

Reference parity: the C++ predictor API
(/root/reference/paddle/fluid/inference/api/paddle_api.h:148
PaddlePredictor/ZeroCopyTensor and analysis_predictor.h:47) lets trained
models serve from non-Python daemons. The TPU-native equivalent is a
serialized StableHLO artifact via jax.export: the pruned inference
Program is traced ONCE into a single XLA computation with the trained
weights baked in as constants, then serialized to

  serving/meta.json          feed/fetch names, shapes, dtypes, buckets
  serving/export_b{N}.bin    jax.export bytes (deserialize + call)
  serving/module_b{N}.mlir   StableHLO text — a C++ PjRt client can
                             compile this module directly, no Python

One export per batch bucket (XLA computations are static-shape; the
loader pads requests up to the nearest bucket, same policy as
inference.Predictor's compile cache).
"""
import json
import os

import numpy as np

MODULE_SUBDIR = "serving"
# v1: feed_batch_dynamic (bool per feed). v2: feed_batch_factor /
# fetch_batch_factor (ints; dim0 = factor * batch, 0 = static).
SERVING_FORMAT_VERSION = 2


def _infer_fn(program, feed_names, fetch_names, scope):
    """Close the trained weights over a pure (feeds) -> fetches function.

    jax.export turns closure arrays into embedded constants, which is
    exactly the frozen-artifact contract: the .bin is self-contained."""
    import jax
    from .framework import executor as ex_mod
    from .framework.trace import TraceContext, trace_block

    persistable = ex_mod._persistable_names(program)
    state = {n: scope.find_var(n) for n in sorted(persistable)
             if scope.find_var(n) is not None}

    def fn(*feeds):
        env = dict(state)
        env.update(zip(feed_names, feeds))
        ctx = TraceContext(program, jax.random.PRNGKey(0), frozenset())
        trace_block(program.global_block(), env, ctx)
        return tuple(env[n] for n in fetch_names)

    return fn


def infer_batch_factors(dyn_dims, overrides=None):
    """Shared batch-factor inference (serving export AND the in-process
    Predictor): `dyn_dims` is [(name, dim0)] for the batch-dynamic
    feeds. A feed's dim0 = factor * batch; the smallest dim0 is taken as
    the batch unless `overrides` ({name: factor}) pins a feed — then the
    batch derives from the overridden feeds (they must agree). Returns
    ({name: factor}, batch). batch 0 (empty request) gives factor 1 to
    every non-overridden feed."""
    overrides = overrides or {}
    if not dyn_dims:
        return {}, None
    base = None
    for name, d0 in dyn_dims:
        if name in overrides:
            f = int(overrides[name])
            if f <= 0 or d0 % f:
                raise ValueError(
                    "feed %r dim0 %d is not a multiple of its declared "
                    "batch factor %r" % (name, d0, overrides[name]))
            b2 = d0 // f
            if base is None:
                base = b2
            elif b2 != base:
                raise ValueError(
                    "overridden feeds disagree on the batch: %r implies "
                    "%d, earlier feeds %d" % (name, b2, base))
    if base is None:
        base = min(d0 for _, d0 in dyn_dims)
    factors = {}
    for name, d0 in dyn_dims:
        if name in overrides:
            factors[name] = int(overrides[name])
        elif base == 0:
            factors[name] = 1
        else:
            if d0 % base:
                raise ValueError(
                    "feed %r leading dim %d is not a multiple of the "
                    "batch %d" % (name, d0, base))
            factors[name] = d0 // base
    return factors, base


def _feed_factors(program, feed_names, example_feed, overrides=None):
    """Per-feed batch factors: feed i's leading dim is factor[i] *
    request_batch (0 = static feed). Factor 1 is the default for
    batch-dynamic feeds; an example feed dict refines it for feeds whose
    leading dim scales as a MULTIPLE of the batch (e.g. BERT's flat
    mask_pos with dim0 = batch * max_preds) — inference takes the
    SMALLEST dynamic leading dim as the batch, so at least one dynamic
    feed must carry dim0 == batch; if none does, pass explicit factors
    via `overrides` ({feed_name: factor})."""
    blk = program.global_block()
    dyn = []
    for name in feed_names:
        shape = list(blk.var(name).shape)
        dyn.append(bool(shape) and shape[0] == -1)
    if not any(dyn):
        return [0] * len(feed_names)
    overrides = overrides or {}
    if example_feed is None:
        return [overrides.get(n, 1) if d else 0
                for n, d in zip(feed_names, dyn)]
    dyn_dims = [(n, np.asarray(example_feed[n]).shape[0])
                for n, d in zip(feed_names, dyn) if d]
    fmap, _ = infer_batch_factors(dyn_dims, overrides)
    return [fmap[n] if d else 0 for n, d in zip(feed_names, dyn)]


def _feed_avals(program, feed_names, batch, factors):
    """ShapeDtypeStructs for the feeds at one bucket size; a leading -1
    (append_batch_size) dim becomes factor * bucket batch."""
    import jax
    from .framework.dtypes import to_jax_dtype
    blk = program.global_block()
    avals = []
    for name, factor in zip(feed_names, factors):
        var = blk.var(name)
        shape = list(var.shape)
        if factor:
            shape[0] = batch * factor
        if any(s is None or s < 0 for s in shape):
            raise ValueError(
                "serving export: feed %r has non-batch dynamic dims %s — "
                "XLA serving artifacts are static-shape" % (name, shape))
        avals.append(jax.ShapeDtypeStruct(tuple(shape),
                                          to_jax_dtype(var.dtype)))
    return avals


def export_serving_artifact(dirname, feeded_var_names, target_vars,
                            executor=None, main_program=None,
                            batch_sizes=(1, 8, 32), scope=None,
                            pruned_program=None, example_feed=None,
                            feed_batch_factors=None):
    """Freeze + export the inference program as StableHLO.

    Writes under dirname/serving/. target_vars may be Variables or names.
    pruned_program skips the clone+prune when the caller (e.g.
    save_inference_model) already froze the program. example_feed (one
    representative feed dict) teaches the export which batch-dynamic
    feeds scale as a MULTIPLE of the request batch (BERT's flat mask_pos
    = batch * max_preds); without it every dynamic feed is assumed
    factor 1. Returns the list of written export paths."""
    import jax
    from jax import export as jax_export
    from .framework.program import default_main_program
    from .framework.scope import global_scope

    if not batch_sizes:
        raise ValueError("serving export needs at least one batch size")
    scope = scope or global_scope()
    target_names = [getattr(v, "name", v) for v in target_vars]
    if pruned_program is not None:
        pruned = pruned_program
    else:
        program = main_program or default_main_program()
        test_prog = program.clone(for_test=True)
        pruned = test_prog._prune(list(feeded_var_names), target_names)

    # build the whole artifact in a temp dir and swap it in at the end:
    # an interrupted re-export must never leave a loadable mix of old and
    # new exports (same commit-point discipline as io._atomic_write)
    final_dir = os.path.join(dirname, MODULE_SUBDIR)
    out_dir = final_dir + ".tmp.%d" % os.getpid()
    if os.path.exists(out_dir):
        import shutil
        shutil.rmtree(out_dir)
    os.makedirs(out_dir)
    fn = _infer_fn(pruned, list(feeded_var_names), target_names, scope)

    factors = _feed_factors(pruned, feeded_var_names, example_feed,
                            overrides=feed_batch_factors)
    dynamic = any(factors)
    buckets = sorted(set(batch_sizes)) if dynamic else [0]

    # which OUTPUTS scale with the batch, and by what factor: compare
    # abstract output shapes at two batch sizes (jax.eval_shape — no
    # compile). Recorded at export so the loader never guesses from
    # runtime shapes (a static dim that happens to equal batch*f must
    # not get sliced).
    fetch_factors = [0] * len(target_names)
    if dynamic:
        o1 = jax.eval_shape(fn, *_feed_avals(pruned, feeded_var_names, 1,
                                             factors))
        o2 = jax.eval_shape(fn, *_feed_avals(pruned, feeded_var_names, 2,
                                             factors))
        for i, (s1, s2) in enumerate(zip(o1, o2)):
            if s1.shape and s2.shape and s2.shape[0] != s1.shape[0]:
                fetch_factors[i] = s2.shape[0] - s1.shape[0]

    written, bucket_meta = [], {}
    for b in buckets:
        avals = _feed_avals(pruned, feeded_var_names, b or 1, factors)
        exported = jax_export.export(jax.jit(fn))(*avals)
        blob = exported.serialize()
        bin_path = os.path.join(out_dir, "export_b%d.bin" % b)
        with open(bin_path, "wb") as f:
            f.write(blob)
        with open(os.path.join(out_dir, "module_b%d.mlir" % b), "w") as f:
            f.write(exported.mlir_module())
        written.append(bin_path)
        bucket_meta[str(b)] = {
            "feeds": [{"name": n, "shape": list(a.shape),
                       "dtype": np.dtype(a.dtype).name}
                      for n, a in zip(feeded_var_names, avals)]}

    meta = {"format_version": SERVING_FORMAT_VERSION,
            "feed_var_names": list(feeded_var_names),
            "fetch_var_names": target_names,
            "dynamic_batch": dynamic,
            "feed_batch_factor": factors,
            "fetch_batch_factor": fetch_factors,
            "buckets": bucket_meta}
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    import shutil
    if os.path.exists(final_dir):
        shutil.rmtree(final_dir)
    os.rename(out_dir, final_dir)
    return [p.replace(out_dir, final_dir) for p in written]


class ServingPredictor(object):
    """Thin loader for the StableHLO artifact: deserialize + call.

    Python twin of the C++ load path (a non-Python service compiles
    module_b{N}.mlir with PjRt instead). Pads requests up to the nearest
    exported bucket and slices results back — the inference.Predictor
    contract."""

    def __init__(self, dirname):
        from jax import export as jax_export
        out_dir = os.path.join(dirname, MODULE_SUBDIR)
        with open(os.path.join(out_dir, "meta.json")) as f:
            self._meta = json.load(f)
        if self._meta["format_version"] > SERVING_FORMAT_VERSION:
            raise ValueError(
                "serving artifact %s has format_version %d, newer than "
                "this library's %d"
                % (dirname, self._meta["format_version"],
                   SERVING_FORMAT_VERSION))
        if "feed_batch_factor" not in self._meta:
            # v1 artifacts: booleans, factor 1 semantics; outputs were
            # sliced when dim0 == bucket (factor 1)
            dyn = self._meta.get("feed_batch_dynamic", [])
            self._meta["feed_batch_factor"] = [1 if d else 0 for d in dyn]
            self._meta["fetch_batch_factor"] = [
                1] * len(self._meta["fetch_var_names"])
        self._feed_names = self._meta["feed_var_names"]
        self._fetch_names = self._meta["fetch_var_names"]
        self._fns = {}
        for key in self._meta["buckets"]:
            with open(os.path.join(out_dir, "export_b%s.bin" % key),
                      "rb") as f:
                self._fns[int(key)] = jax_export.deserialize(f.read())

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def _bucket(self, n):
        for b in sorted(self._fns):
            if n <= b:
                return b
        raise ValueError(
            "request batch %d exceeds the largest exported bucket %d — "
            "re-export with a larger batch_sizes entry"
            % (n, max(self._fns)))

    def run(self, inputs):
        """inputs: dict name -> array (or list aligned with feed names).
        Returns list of np arrays aligned with fetch names."""
        if isinstance(inputs, (list, tuple)):
            inputs = dict(zip(self._feed_names, inputs))
        if not self._meta["dynamic_batch"]:
            outs = self._fns[0].call(
                *[np.asarray(inputs[n]) for n in self._feed_names])
            return [np.asarray(o) for o in outs]
        # the request batch comes from the feeds' recorded batch factors
        # (feed i's dim0 = factor_i * batch) — never from dict order
        factors = self._meta["feed_batch_factor"]
        n = None
        for name, f in zip(self._feed_names, factors):
            if f:
                got = np.asarray(inputs[name]).shape[0]
                if got % f:
                    raise ValueError(
                        "feed %r has %d rows, not a multiple of its "
                        "batch factor %d" % (name, got, f))
                if n is None:
                    n = got // f
                elif got // f != n:
                    raise ValueError(
                        "batch-dynamic feeds disagree on batch size: "
                        "feed %r implies batch %d, earlier feeds %d"
                        % (name, got // f, n))
        b = self._bucket(n)
        feeds = []
        for name, f in zip(self._feed_names, factors):
            arr = np.asarray(inputs[name])
            if f and arr.shape[0] != b * f:
                pad = [(0, b * f - arr.shape[0])] + \
                    [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad)
            feeds.append(arr)
        outs = self._fns[b].call(*feeds)
        # slice batch-scaled outputs per the EXPORT-time factors — never
        # guessed from runtime shapes (a static dim that happens to
        # equal b*f must not be truncated)
        fetch_factors = self._meta["fetch_batch_factor"]
        sliced = []
        for o, f in zip(outs, fetch_factors):
            o = np.asarray(o)
            if f and np.ndim(o) > 0 and o.shape[0] == b * f:
                o = o[:n * f]
            sliced.append(o)
        return sliced


def load_serving_artifact(dirname):
    return ServingPredictor(dirname)
