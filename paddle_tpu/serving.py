"""Deployable serving artifacts — "train here, serve anywhere".

Reference parity: the C++ predictor API
(/root/reference/paddle/fluid/inference/api/paddle_api.h:148
PaddlePredictor/ZeroCopyTensor and analysis_predictor.h:47) lets trained
models serve from non-Python daemons. The TPU-native equivalent is a
serialized StableHLO artifact via jax.export: the pruned inference
Program is traced ONCE into a single XLA computation with the trained
weights baked in as constants, then serialized to

  serving/meta.json          feed/fetch names, shapes, dtypes, buckets
  serving/export_b{N}.bin    jax.export bytes (deserialize + call)
  serving/module_b{N}.mlir   StableHLO text — a C++ PjRt client can
                             compile this module directly, no Python

One export per batch bucket (XLA computations are static-shape; the
loader pads requests up to the nearest bucket, same policy as
inference.Predictor's compile cache).
"""
import json
import os

import numpy as np

MODULE_SUBDIR = "serving"
SERVING_FORMAT_VERSION = 1


def _infer_fn(program, feed_names, fetch_names, scope):
    """Close the trained weights over a pure (feeds) -> fetches function.

    jax.export turns closure arrays into embedded constants, which is
    exactly the frozen-artifact contract: the .bin is self-contained."""
    import jax
    from .framework import executor as ex_mod
    from .framework.trace import TraceContext, trace_block

    persistable = ex_mod._persistable_names(program)
    state = {n: scope.find_var(n) for n in sorted(persistable)
             if scope.find_var(n) is not None}

    def fn(*feeds):
        env = dict(state)
        env.update(zip(feed_names, feeds))
        ctx = TraceContext(program, jax.random.PRNGKey(0), frozenset())
        trace_block(program.global_block(), env, ctx)
        return tuple(env[n] for n in fetch_names)

    return fn


def _feed_avals(program, feed_names, batch):
    """ShapeDtypeStructs for the feeds at one bucket size; a leading -1
    (append_batch_size) dim becomes the bucket batch. Returns
    (avals, batch_dyn) where batch_dyn[i] says feed i's dim 0 is the
    request batch — the loader pads ONLY those feeds."""
    import jax
    from .framework.dtypes import to_jax_dtype
    blk = program.global_block()
    avals, batch_dyn = [], []
    for name in feed_names:
        var = blk.var(name)
        shape = list(var.shape)
        dyn = bool(shape) and shape[0] == -1
        if dyn:
            shape[0] = batch
        batch_dyn.append(dyn)
        if any(s is None or s < 0 for s in shape):
            raise ValueError(
                "serving export: feed %r has non-batch dynamic dims %s — "
                "XLA serving artifacts are static-shape" % (name, shape))
        avals.append(jax.ShapeDtypeStruct(tuple(shape),
                                          to_jax_dtype(var.dtype)))
    return avals, batch_dyn


def export_serving_artifact(dirname, feeded_var_names, target_vars,
                            executor=None, main_program=None,
                            batch_sizes=(1, 8, 32), scope=None,
                            pruned_program=None):
    """Freeze + export the inference program as StableHLO.

    Writes under dirname/serving/. target_vars may be Variables or names.
    pruned_program skips the clone+prune when the caller (e.g.
    save_inference_model) already froze the program. Returns the list of
    written export paths."""
    import jax
    from jax import export as jax_export
    from .framework.program import default_main_program
    from .framework.scope import global_scope

    if not batch_sizes:
        raise ValueError("serving export needs at least one batch size")
    scope = scope or global_scope()
    target_names = [getattr(v, "name", v) for v in target_vars]
    if pruned_program is not None:
        pruned = pruned_program
    else:
        program = main_program or default_main_program()
        test_prog = program.clone(for_test=True)
        pruned = test_prog._prune(list(feeded_var_names), target_names)

    # build the whole artifact in a temp dir and swap it in at the end:
    # an interrupted re-export must never leave a loadable mix of old and
    # new exports (same commit-point discipline as io._atomic_write)
    final_dir = os.path.join(dirname, MODULE_SUBDIR)
    out_dir = final_dir + ".tmp.%d" % os.getpid()
    if os.path.exists(out_dir):
        import shutil
        shutil.rmtree(out_dir)
    os.makedirs(out_dir)
    fn = _infer_fn(pruned, list(feeded_var_names), target_names, scope)

    _, batch_dyn = _feed_avals(pruned, feeded_var_names, batch_sizes[0])
    dynamic = any(batch_dyn)
    buckets = sorted(set(batch_sizes)) if dynamic else [0]

    written, bucket_meta = [], {}
    for b in buckets:
        avals, _ = _feed_avals(pruned, feeded_var_names, b or 1)
        exported = jax_export.export(jax.jit(fn))(*avals)
        blob = exported.serialize()
        bin_path = os.path.join(out_dir, "export_b%d.bin" % b)
        with open(bin_path, "wb") as f:
            f.write(blob)
        with open(os.path.join(out_dir, "module_b%d.mlir" % b), "w") as f:
            f.write(exported.mlir_module())
        written.append(bin_path)
        bucket_meta[str(b)] = {
            "feeds": [{"name": n, "shape": list(a.shape),
                       "dtype": np.dtype(a.dtype).name}
                      for n, a in zip(feeded_var_names, avals)]}

    meta = {"format_version": SERVING_FORMAT_VERSION,
            "feed_var_names": list(feeded_var_names),
            "fetch_var_names": target_names,
            "dynamic_batch": dynamic,
            "feed_batch_dynamic": batch_dyn,
            "buckets": bucket_meta}
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    import shutil
    if os.path.exists(final_dir):
        shutil.rmtree(final_dir)
    os.rename(out_dir, final_dir)
    return [p.replace(out_dir, final_dir) for p in written]


class ServingPredictor(object):
    """Thin loader for the StableHLO artifact: deserialize + call.

    Python twin of the C++ load path (a non-Python service compiles
    module_b{N}.mlir with PjRt instead). Pads requests up to the nearest
    exported bucket and slices results back — the inference.Predictor
    contract."""

    def __init__(self, dirname):
        from jax import export as jax_export
        out_dir = os.path.join(dirname, MODULE_SUBDIR)
        with open(os.path.join(out_dir, "meta.json")) as f:
            self._meta = json.load(f)
        if self._meta["format_version"] > SERVING_FORMAT_VERSION:
            raise ValueError(
                "serving artifact %s has format_version %d, newer than "
                "this library's %d"
                % (dirname, self._meta["format_version"],
                   SERVING_FORMAT_VERSION))
        self._feed_names = self._meta["feed_var_names"]
        self._fetch_names = self._meta["fetch_var_names"]
        self._fns = {}
        for key in self._meta["buckets"]:
            with open(os.path.join(out_dir, "export_b%s.bin" % key),
                      "rb") as f:
                self._fns[int(key)] = jax_export.deserialize(f.read())

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def _bucket(self, n):
        for b in sorted(self._fns):
            if n <= b:
                return b
        raise ValueError(
            "request batch %d exceeds the largest exported bucket %d — "
            "re-export with a larger batch_sizes entry"
            % (n, max(self._fns)))

    def run(self, inputs):
        """inputs: dict name -> array (or list aligned with feed names).
        Returns list of np arrays aligned with fetch names."""
        if isinstance(inputs, (list, tuple)):
            inputs = dict(zip(self._feed_names, inputs))
        if not self._meta["dynamic_batch"]:
            outs = self._fns[0].call(
                *[np.asarray(inputs[n]) for n in self._feed_names])
            return [np.asarray(o) for o in outs]
        # the request batch comes from a feed whose exported dim 0 IS the
        # batch (feed_batch_dynamic from export) — never from dict order
        batch_dyn = self._meta["feed_batch_dynamic"]
        n = None
        for name, dyn in zip(self._feed_names, batch_dyn):
            if dyn:
                got = np.asarray(inputs[name]).shape[0]
                if n is None:
                    n = got
                elif got != n:
                    raise ValueError(
                        "batch-dynamic feeds disagree on batch size: "
                        "feed %r has %d rows, earlier feeds have %d"
                        % (name, got, n))
        b = self._bucket(n)
        feeds = []
        for name, dyn in zip(self._feed_names, batch_dyn):
            arr = np.asarray(inputs[name])
            if dyn and arr.shape[0] != b:
                pad = [(0, b - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad)
            feeds.append(arr)
        outs = self._fns[b].call(*feeds)
        return [np.asarray(o)[:n]
                if np.ndim(o) > 0 and np.shape(o)[0] == b else np.asarray(o)
                for o in outs]


def load_serving_artifact(dirname):
    return ServingPredictor(dirname)
