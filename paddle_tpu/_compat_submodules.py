"""Reference deep-module-path compatibility.

Several reference subsystems are PACKAGES of many small modules
(`contrib/slim/prune/{pruner,prune_strategy,...}.py`) whose capability
this framework implements in one flat module (`contrib/slim/prune.py`).
Scripts importing the deep paths (`from paddle.fluid.contrib.slim.prune
.pruner import Pruner`) should still port by renaming the root package,
so each reference child path is registered here as a VIRTUAL module
re-exporting the flat implementation's objects — one instance of the
code, two import spellings. Paths whose capability is N/A on TPU expose
guidance errors (see PORTING.md "Capability substitutions").
"""
import importlib
import importlib.machinery
import sys
import types


def _virtual(fullname, doc, exports):
    parent_name, _, child = fullname.rpartition(".")
    parent = importlib.import_module(parent_name)
    if not hasattr(parent, "__path__"):
        # a flat module gaining virtual children must look like a
        # package, or `import parent.child` refuses before consulting
        # sys.modules/meta_path ("'parent' is not a package")
        parent.__path__ = []
    mod = types.ModuleType(fullname, doc)
    for k, v in exports.items():
        setattr(mod, k, v)
    mod.__all__ = sorted(exports)
    mod.__spec__ = importlib.machinery.ModuleSpec(fullname, None)
    sys.modules[fullname] = mod
    setattr(parent, child, mod)
    return mod


def _guided(fullname, doc, guidance):
    mod = _virtual(fullname, doc, {})

    def _getattr(name, _g=guidance):
        if name.startswith("__"):     # import-machinery dunder probes
            raise AttributeError(name)
        raise NotImplementedError(_g)

    mod.__getattr__ = _getattr
    return mod


def install():
    from .contrib.slim import prune as _prune
    from .contrib.slim import core as _score
    from .contrib.slim import distill as _distill
    from .contrib.slim import qat as _qat
    from .contrib.slim import distillation as _  # noqa: F401,F811
    from .contrib.slim import quantization as _  # noqa: F401,F811
    from .contrib import mixed_precision as _mp
    from .contrib import quantize as _cq
    from .contrib import reader as _crdr
    from .contrib import extend_optimizer as _eo
    from .distributed import fleet as _fleet
    from .distributed.mesh import DistributedStrategy as _DS

    V = _virtual
    V("paddle_tpu.contrib.slim.prune.pruner",
      "ref slim/prune/pruner.py — pruners live in slim/prune.py",
      {"Pruner": _prune.Pruner, "MagnitudePruner": _prune.MagnitudePruner,
       "StructurePruner": _prune.StructurePruner})
    V("paddle_tpu.contrib.slim.prune.prune_strategy",
      "ref slim/prune/prune_strategy.py — strategy machinery lives in "
      "slim/prune.py + slim/core.py",
      {"PruneHelper": _prune.PruneHelper, "sensitivity":
       _prune.sensitivity})
    V("paddle_tpu.contrib.slim.prune.auto_prune_strategy",
      "ref slim/prune/auto_prune_strategy.py — the sensitivity sweep is "
      "slim.prune.sensitivity", {"sensitivity": _prune.sensitivity})
    V("paddle_tpu.contrib.slim.core.compressor",
      "ref slim/core/compressor.py",
      {"Compressor": _score.Compressor, "Context": _score.Context})
    V("paddle_tpu.contrib.slim.core.strategy",
      "ref slim/core/strategy.py — strategies are plain callables on "
      "Context here", {"Compressor": _score.Compressor})
    V("paddle_tpu.contrib.slim.core.config",
      "ref slim/core/config.py — YAML config factory; paddle_tpu "
      "Compressor takes plain Python config",
      {"Compressor": _score.Compressor})
    V("paddle_tpu.contrib.slim.distillation.distiller",
      "ref slim/distillation/distiller.py",
      {k: getattr(_distill, k) for k in getattr(_distill, "__all__",
                                                dir(_distill))
       if not k.startswith("_")})
    V("paddle_tpu.contrib.slim.distillation.distillation_strategy",
      "ref slim/distillation/distillation_strategy.py",
      {"merge": _distill.merge})
    for child in ("quantization_pass", "quantization_strategy",
                  "post_training_quantization"):
        V("paddle_tpu.contrib.slim.quantization." + child,
          "ref slim/quantization/%s.py — QAT/PTQ passes live in "
          "slim/qat.py + contrib/quantize.py" % child,
          {"quant_aware": _qat.quant_aware, "convert": _qat.convert})
    for child in ("quantization_mkldnn_pass",
                  "mkldnn_post_training_strategy"):
        _guided("paddle_tpu.contrib.slim.quantization." + child,
                "ref slim/quantization/%s.py" % child,
                "MKL-DNN passes target x86 inference; on TPU use "
                "slim.qat.quant_aware/convert (XLA is the engine)")
    V("paddle_tpu.contrib.quantize.quantize_transpiler",
      "ref contrib/quantize/quantize_transpiler.py — PTQ helpers live "
      "in contrib/quantize.py",
      {k: getattr(_cq, k) for k in dir(_cq) if not k.startswith("_")})
    V("paddle_tpu.contrib.extend_optimizer."
      "extend_optimizer_with_weight_decay",
      "ref contrib/extend_optimizer/extend_optimizer_with_weight_decay"
      ".py — AdamW-style decoupled decay is optimizer.AdamW",
      {"GradientMergeOptimizer": _eo.GradientMergeOptimizer})
    V("paddle_tpu.contrib.mixed_precision.fp16_lists",
      "ref contrib/mixed_precision/fp16_lists.py",
      {"AutoMixedPrecisionLists": _mp.AutoMixedPrecisionLists})
    V("paddle_tpu.contrib.mixed_precision.decorator",
      "ref contrib/mixed_precision/decorator.py",
      {"decorate": _mp.decorate,
       "OptimizerWithMixedPrecision": _mp.OptimizerWithMixedPrecision})
    V("paddle_tpu.contrib.mixed_precision.fp16_utils",
      "ref contrib/mixed_precision/fp16_utils.py — cast plumbing is "
      "internal to mixed_precision.py on paddle_tpu",
      {"AutoMixedPrecisionLists": _mp.AutoMixedPrecisionLists})
    V("paddle_tpu.contrib.reader.distributed_reader",
      "ref contrib/reader/distributed_reader.py",
      {"distributed_batch_reader": _crdr.distributed_batch_reader})

    # incubate.fleet.parameter_server.{distribute_transpiler,pslib} trees
    V("paddle_tpu.incubate.fleet.parameter_server.distribute_transpiler",
      "ref incubate/fleet/parameter_server/distribute_transpiler/ — "
      "pserver fleet is N/A on TPU; the collective fleet is the "
      "implementation (PORTING.md)",
      {"fleet": _fleet, "DistributedStrategy": _DS})
    V("paddle_tpu.incubate.fleet.parameter_server.distribute_transpiler."
      "distributed_strategy",
      "ref .../distribute_transpiler/distributed_strategy.py",
      {"DistributedStrategy": _DS})
    for child in ("optimizer_factory", "ps_pb2", "node"):
        _guided("paddle_tpu.incubate.fleet.parameter_server.pslib."
                + child,
                "ref incubate/fleet/parameter_server/pslib/%s.py" % child,
                "PSLib configures Baidu's pserver binary; on paddle_tpu "
                "sparse tables are row-sharded mesh state "
                "(distributed/sharded_embedding.py)")
