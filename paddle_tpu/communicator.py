"""fluid.communicator (ref python/paddle/fluid/communicator.py).

The reference Communicator drives ASYNC parameter-server sends — a
mechanism that exists to hide commodity-network latency. On a TPU pod,
synchronous data parallelism over ICI is strictly faster and simpler
(see PORTING.md capability table), so constructing a Communicator
raises with that guidance instead of silently doing nothing.
"""

__all__ = ["Communicator"]


class Communicator(object):
    def __init__(self, program=None):
        raise NotImplementedError(
            "Async communicator modes are N/A on TPU pods: synchronous "
            "dp over ICI (CompiledProgram/fleet with a mesh) replaces "
            "GEO/async-SGD. See PORTING.md 'Capability substitutions'.")
