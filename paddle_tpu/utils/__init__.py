"""Utility helpers (ref python/paddle/utils/__init__.py): training-curve
plotting + legacy v1 image preprocessing."""
from . import plot
from . import image_util
from .plot import Ploter, PlotData

__all__ = ["plot", "image_util", "Ploter", "PlotData"]
