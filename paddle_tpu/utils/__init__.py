"""Utility helpers (ref python/paddle/utils/__init__.py): training-curve
plotting + legacy v1 image preprocessing + torch weight import."""
from . import plot
from . import image_util
from . import plotcurve
from . import preprocess_util
from . import preprocess_img
from . import show_pb
from . import torch2paddle
from .plot import Ploter, PlotData

__all__ = ["plot", "image_util", "plotcurve", "preprocess_util",
           "preprocess_img", "show_pb", "torch2paddle", "Ploter",
           "PlotData"]
