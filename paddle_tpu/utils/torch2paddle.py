"""Import torch model weights into paddle_tpu parameters.

Reference parity: python/paddle/utils/torch2paddle.py — the reference
converted (lua-)torch model files into Paddle parameter files. The
capability, modernized: map a pytorch ``state_dict`` onto the parameters
of a Program's scope, with the layout transposes the two conventions
need (torch nn.Linear stores (out, in); fluid fc stores (in, out)).
"""
import numpy as np

__all__ = ["torch_state_dict_to_numpy", "load_torch_parameters",
           "save_net_parameters"]


def torch_state_dict_to_numpy(state_dict):
    """{name: np.ndarray} from a pytorch state_dict (tensors detached
    and moved to host)."""
    out = {}
    for k, v in state_dict.items():
        if hasattr(v, "detach"):
            v = v.detach().cpu().numpy()
        out[k] = np.asarray(v)
    return out


def load_torch_parameters(scope, state_dict, name_map,
                          transpose_linear=True, transpose_names=None):
    """Copy torch weights into ``scope``.

    name_map: {torch_param_name: paddle_var_name}. Rectangular linear/fc
    weights are transposed automatically ((out,in) -> (in,out)) when
    that is what makes the shapes agree; conv weights share the OIHW
    layout and pass through. SQUARE 2-D weights are ambiguous — both
    orientations fit — so they must be named in ``transpose_names``
    (transpose) or omitted from it (copy as-is) explicitly, otherwise
    this raises rather than guess. Returns the paddle names written.
    """
    arrays = torch_state_dict_to_numpy(state_dict)
    transpose_names = set(transpose_names or ())
    written = []
    for tname, pname in name_map.items():
        if tname not in arrays:
            raise KeyError("torch state_dict has no %r (have: %s...)"
                           % (tname, ", ".join(list(arrays)[:5])))
        arr = arrays[tname]
        existing = scope.find_var(pname)
        if existing is None:
            raise KeyError(
                "scope has no variable %r to receive %r — run the "
                "startup program (parameter init) first so shapes are "
                "known for orientation checks" % (pname, tname))
        if arr.ndim == 2:
            square = arr.shape[0] == arr.shape[1]
            if tname in transpose_names:
                arr = arr.T
            elif square and transpose_linear \
                    and tuple(np.shape(existing)) == arr.shape:
                raise ValueError(
                    "square weight %r -> %r is orientation-ambiguous: "
                    "list it in transpose_names to transpose (torch "
                    "nn.Linear) or pass transpose_linear=False to copy "
                    "as-is (embeddings etc.)" % (tname, pname))
            elif transpose_linear \
                    and tuple(np.shape(existing)) == arr.T.shape \
                    and tuple(np.shape(existing)) != arr.shape:
                arr = arr.T
        if tuple(np.shape(existing)) != arr.shape:
            raise ValueError(
                "shape mismatch importing %r -> %r: torch %s vs paddle %s"
                % (tname, pname, arr.shape, tuple(np.shape(existing))))
        scope.set_var(pname, arr)
        written.append(pname)
    return written


def save_net_parameters(state_dict, name_map, output_dir,
                        transpose_names=None):
    """Convert a torch state_dict to a parameter DIRECTORY loadable by
    ``paddle_tpu.io.load_params(exe, output_dir)`` (ref
    save_net_parameters): writes ``<output_dir>/params.npz``. 2-D
    weights named in ``transpose_names`` are transposed ((out,in) ->
    (in,out) for torch nn.Linear); with no target shapes available at
    save time the transpose set must be explicit."""
    import os
    arrays = torch_state_dict_to_numpy(state_dict)
    missing = [t for t in name_map if t not in arrays]
    if missing:
        raise KeyError("torch state_dict has no %r" % (missing[0],))
    transpose_names = set(transpose_names or ())
    out = {}
    for t, p in name_map.items():
        arr = arrays[t]
        out[p] = arr.T if t in transpose_names and arr.ndim == 2 else arr
    os.makedirs(output_dir, exist_ok=True)
    np.savez(os.path.join(output_dir, "params.npz"), **out)
    return sorted(name_map.values())
