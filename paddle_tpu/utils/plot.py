"""Training-curve plotting (ref python/paddle/utils/plot.py).

The reference Ploter draws live matplotlib curves in notebooks and
falls back to printing in terminals.  Headless TPU pods rarely have a
display, so the terminal path is primary here: append() always records
(and prints); plot() renders via matplotlib when it is importable and a
save path is given, else it is a no-op beyond the recorded history
(inspectable via ``ploter.data``).
"""

__all__ = ["PlotData", "Ploter"]


class PlotData(object):
    """One curve: step/value arrays (ref plot.py:19)."""

    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter(object):
    """Multi-curve recorder (ref plot.py:33): construct with curve
    titles, append(title, step, value) during training."""

    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {}
        for title in args:
            self.__plot_data__[title] = PlotData()

    @property
    def data(self):
        return self.__plot_data__

    def append(self, title, step, value):
        assert isinstance(title, str)
        assert title in self.__plot_data__
        data = self.__plot_data__[title]
        assert isinstance(data, PlotData)
        data.append(step, value)
        print("%s - step %s: %s" % (title, step, value))

    def plot(self, path=None):
        """Render all curves; writes a PNG when matplotlib is available
        and ``path`` is given, otherwise keeps terminal-only output."""
        if path is None:
            return
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except Exception:
            return
        for title in self.__args__:
            d = self.__plot_data__[title]
            plt.plot(d.step, d.value, label=title)
        plt.legend()
        plt.savefig(path)
        plt.clf()

    def reset(self):
        for key in self.__plot_data__:
            self.__plot_data__[key].reset()
