"""Inspect serialized model artifacts from the command line.

Reference parity: python/paddle/utils/show_pb.py (print a serialized
ProgramDesc protobuf). This framework serializes Programs as JSON
(framework/program.py to_json) and inference artifacts as
model.json+manifest, so ``show`` pretty-prints those; ``read_proto``
keeps the reference entry-point name and explains the format change.
"""
import json
import os
import sys

__all__ = ["read_proto", "show", "main"]


def read_proto(file, message=None):
    """The reference parsed framework.proto ProgramDesc here; this
    framework has no protobuf IR — point callers at the JSON loader."""
    raise NotImplementedError(
        "paddle_tpu serializes Programs as JSON, not protobuf; use "
        "show(path) here or paddle_tpu.Program.from_json directly")


def _summarize_program(doc):
    blocks = doc.get("blocks", [])
    lines = ["Program: %d block(s), version %s"
             % (len(blocks), doc.get("version", "?"))]
    for bi, blk in enumerate(blocks):
        ops = blk.get("ops", [])
        vars_ = blk.get("vars", {})
        lines.append("  block %d: %d vars, %d ops" % (bi, len(vars_),
                                                      len(ops)))
        for op in ops:
            outs = op.get("outputs", {})
            out0 = next(iter(outs.values()), [""])
            lines.append("    %-24s -> %s" % (op.get("type", "?"),
                                              ", ".join(out0)))
    return "\n".join(lines)


def show(path, out=None):
    """Pretty-print a Program JSON file or a saved inference-model
    directory (model.json)."""
    out = out or sys.stdout
    if os.path.isdir(path):
        path = os.path.join(path, "__model__.json")
    with open(path, "r") as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "program" in doc:
        # inference artifact (io.py save_inference_model): header + IR
        header = {k: v for k, v in doc.items()
                  if k not in ("program", "param_manifest")}
        out.write("Inference artifact %s\n" % json.dumps(header,
                                                         sort_keys=True))
        doc = doc["program"]
    out.write(_summarize_program(doc) + "\n")


def main(argv):  # pragma: no cover - CLI veneer
    if len(argv) != 1:
        sys.stderr.write("usage: python -m paddle_tpu.utils.show_pb "
                         "<program.json | inference_model_dir>\n")
        return 1
    show(argv[0])
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main(sys.argv[1:]))
