"""Image-classification corpus builder.

Reference parity: python/paddle/utils/preprocess_img.py — resize images,
walk a class-per-directory corpus, and emit the block files
preprocess_util's DataBatcher defines.
"""
import os

import numpy as np

from . import preprocess_util
from .preprocess_util import Dataset, list_images

__all__ = ["resize_image", "DiskImage", "ImageClassificationDatasetCreater"]


def resize_image(img, target_size):
    """Resize a PIL image so its SHORT side equals target_size (aspect
    preserved) — the classification-pipeline convention."""
    w, h = img.size
    if w < h:
        nw, nh = target_size, max(1, int(round(h * target_size / w)))
    else:
        nw, nh = max(1, int(round(w * target_size / h))), target_size
    return img.resize((nw, nh))


class DiskImage(object):
    """A lazily-loaded image file + its label."""

    def __init__(self, path, target_size):
        self.path = path
        self.target_size = target_size

    def read_image(self):
        from PIL import Image
        with Image.open(self.path) as img:
            img = img.convert("RGB")
            img = resize_image(img, self.target_size)
            return np.asarray(img, np.uint8)


class ImageClassificationDatasetCreater(preprocess_util.DatasetCreater):
    """Build block files from train/ and test/ class-per-subdir trees of
    images (each sample = (HWC uint8 array, int label))."""

    def __init__(self, data_path, target_size=32, color=True):
        super(ImageClassificationDatasetCreater, self).__init__(data_path)
        self.target_size = target_size
        self.color = color
        self.keys = ["image", "label"]

    def create_dataset_from_dir(self, path):
        labels = preprocess_util.get_label_set_from_dir(path)
        data = []
        for cls, label in sorted(labels.items()):
            cls_dir = os.path.join(path, cls)
            for fname in list_images(cls_dir):
                img = DiskImage(os.path.join(cls_dir, fname),
                                self.target_size).read_image()
                if not self.color:
                    img = img.mean(axis=2).astype(np.uint8)
                data.append((img, label))
        return Dataset(data, self.keys)
