"""Image-classification corpus builder.

Reference parity: python/paddle/utils/preprocess_img.py — resize images,
walk a class-per-directory corpus, and emit the block files
preprocess_util's DataBatcher defines.
"""
import os

import numpy as np

from . import preprocess_util
from .image_util import resize_image as _resize_short_np
from .preprocess_util import Dataset, list_images

__all__ = ["resize_image", "DiskImage", "ImageClassificationDatasetCreater"]


def resize_image(img, target_size):
    """Resize a PIL image so its SHORT side equals target_size (aspect
    preserved). One implementation package-wide: delegates to
    image_util.resize_image / dataset.image.resize_short — note this
    uses that path's floor-division long-side rounding and BILINEAR
    filter (not PIL's round()/BICUBIC), so regenerated corpora may
    differ from pre-consolidation ones by one pixel on the long side."""
    from PIL import Image
    return Image.fromarray(_resize_short_np(img, target_size))


class DiskImage(object):
    """A lazily-loaded image file + its label."""

    def __init__(self, path, target_size):
        self.path = path
        self.target_size = target_size

    def read_image(self):
        from PIL import Image
        with Image.open(self.path) as img:
            img = img.convert("RGB")
            return np.asarray(_resize_short_np(img, self.target_size),
                              np.uint8)


class ImageClassificationDatasetCreater(preprocess_util.DatasetCreater):
    """Build block files from train/ and test/ class-per-subdir trees of
    images (each sample = (HWC uint8 array, int label))."""

    def __init__(self, data_path, target_size=32, color=True):
        super(ImageClassificationDatasetCreater, self).__init__(data_path)
        self.target_size = target_size
        self.color = color
        self.keys = ["image", "label"]

    def create_dataset_from_dir(self, path, label_set=None):
        # label_set comes from the TRAIN split (DatasetCreater.
        # create_batches) so test labels can't silently renumber when a
        # class is missing from test/
        labels = (label_set if label_set is not None
                  else preprocess_util.get_label_set_from_dir(path))
        data = []
        for cls in preprocess_util.list_dirs(path):
            if cls not in labels:
                raise ValueError(
                    "class directory %r in %s is absent from the train "
                    "label set %r" % (cls, path, sorted(labels)))
            cls_dir = os.path.join(path, cls)
            for fname in list_images(cls_dir):
                img = DiskImage(os.path.join(cls_dir, fname),
                                self.target_size).read_image()
                if not self.color:
                    img = img.mean(axis=2).astype(np.uint8)
                data.append((img, labels[cls]))
        return Dataset(data, self.keys)
