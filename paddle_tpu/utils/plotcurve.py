"""Plot training curves from a captured training log.

Reference parity: python/paddle/utils/plotcurve.py (plot_paddle_curve) —
grep metric values out of a training log and plot them. Understands both
the classic ``key=value`` log style and the trainer-loop debug prints
this framework emits (``step N: name=[v]``).
"""
import re
import sys

__all__ = ["extract_curve", "plot_paddle_curve", "main"]

_PAT = re.compile(r"([A-Za-z_][\w.\[\]]*)\s*=\s*\[?([-+0-9.eE]+)\]?")


def extract_curve(keys, lines):
    """{key: [values...]} for every requested key found in the lines."""
    out = {k: [] for k in keys}
    want = set(keys)
    for line in lines:
        for name, val in _PAT.findall(line):
            if name in want:
                try:
                    out[name].append(float(val))
                except ValueError:
                    pass
    return out


def plot_paddle_curve(keys, inputfile, outputfile, format="png",
                      show_fig=False):
    """Plot each key's series from ``inputfile`` (a file object or path)
    into ``outputfile``. Requires matplotlib; raises with guidance when
    it is absent (zero-egress images often omit it)."""
    close = False
    if isinstance(inputfile, str):
        inputfile = open(inputfile, "r")
        close = True
    try:
        curves = extract_curve(keys, inputfile)
    finally:
        if close:
            inputfile.close()
    if not any(curves.values()):
        raise ValueError("no values found for keys %r" % (keys,))
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError(
            "plot_paddle_curve needs matplotlib; pip install matplotlib "
            "or use extract_curve() and plot with your own tooling")
    fig, ax = plt.subplots()
    for k, vals in curves.items():
        if vals:
            ax.plot(range(len(vals)), vals, label=k)
    ax.set_xlabel("sample")
    ax.legend()
    fig.savefig(outputfile, format=format)
    if show_fig:  # pragma: no cover - interactive
        plt.show()
    plt.close(fig)
    return curves


def main(argv):  # pragma: no cover - CLI veneer
    if len(argv) < 3:
        sys.stderr.write(
            "usage: python -m paddle_tpu.utils.plotcurve key... "
            "logfile out.png\n")
        return 1
    *keys, infile, outfile = argv
    plot_paddle_curve(keys, infile, outfile)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main(sys.argv[1:]))
