"""Legacy v1 image helpers (ref python/paddle/utils/image_util.py).

Pure numpy/PIL re-implementations of the v1-era preprocessing calls —
the modern equivalents live in paddle_tpu.dataset.image; these exist so
old scripts keep running.  Images are HWC uint8/float arrays.
"""
import numpy as np

from ..dataset import image as _img

__all__ = ["resize_image", "flip", "crop_img", "preprocess_img",
           "load_image", "oversample", "ImageTransformer"]


def resize_image(img, target_size):
    """Resize the SHORT edge to target_size (ref image_util.py:20)."""
    return _img.resize_short(np.asarray(img), target_size)


def flip(im):
    """Horizontal mirror (ref image_util.py:33)."""
    im = np.asarray(im)
    if im.ndim == 3:
        return im[:, ::-1, :]
    return im[:, ::-1]


def crop_img(im, inner_size, color=True, test=True):
    """Center crop in test mode, random crop (+ random flip) in train
    mode (ref image_util.py:45)."""
    im = np.asarray(im)
    if test:
        return _img.center_crop(im, inner_size, is_color=color)
    out = _img.random_crop(im, inner_size, is_color=color)
    if np.random.randint(2):
        out = flip(out)
    return out


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """crop -> CHW float -> mean subtract (ref image_util.py:96)."""
    im = crop_img(im, crop_size, color=color, test=not is_train)
    im = _img.to_chw(im).astype("float32") if im.ndim == 3 \
        else im.astype("float32")
    if img_mean is not None:
        mean = np.asarray(img_mean, np.float32)
        if im.ndim == 3:
            im = im - mean.reshape(im.shape[0], 1, 1)
        else:
            # grayscale HxW: only a scalar mean is meaningful
            im = im - np.float32(mean.reshape(-1)[0])
    return im.flatten()


def load_image(img_path, is_color=True):
    return _img.load_image(img_path, is_color)


def oversample(img, crop_dims):
    """10-crop oversampling: 4 corners + center, mirrored
    (ref image_util.py:144).  img: list/array of HWC images."""
    imgs = [np.asarray(i) for i in (img if isinstance(img, (list, tuple))
                                    else [img])]
    ch, cw = crop_dims
    out = []
    for im in imgs:
        h, w = im.shape[:2]
        anchors = [(0, 0), (0, w - cw), (h - ch, 0), (h - ch, w - cw),
                   ((h - ch) // 2, (w - cw) // 2)]
        for (y, x) in anchors:
            c = im[y:y + ch, x:x + cw]
            out.append(c)
            out.append(c[:, ::-1])
    return np.stack(out)


class ImageTransformer(object):
    """Stateful channel-order/mean transformer (ref image_util.py:183)."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color=True):
        self.transpose = transpose
        self.channel_swap = channel_swap
        self.mean = None if mean is None else np.array(mean,
                                                       np.float32)
        self.is_color = is_color

    def set_transpose(self, order):
        self.transpose = order

    def set_channel_swap(self, order):
        self.channel_swap = order

    def set_mean(self, mean):
        self.mean = None if mean is None else np.array(mean, np.float32)

    def transformer(self, data):
        data = np.asarray(data, np.float32)
        if self.transpose is not None:
            data = data.transpose(self.transpose)
        if self.channel_swap is not None:
            data = data[np.asarray(self.channel_swap)]
        if self.mean is not None:
            mean = self.mean
            if mean.ndim == 1 and data.ndim == 3:
                mean = mean[:, None, None]
            data = data - mean
        return data
