"""Dataset-directory preprocessing helpers.

Reference parity: python/paddle/utils/preprocess_util.py — walk a
class-per-subdirectory corpus, assign labels, split train/test, and
batch samples into pickled block files the readers can stream.
"""
import os
import pickle
import random

__all__ = ["save_file", "save_list", "exclude_pattern", "list_dirs",
           "list_images", "list_files", "get_label_set_from_dir",
           "Label", "Dataset", "DataBatcher", "DatasetCreater"]


def save_file(data, filename):
    """Pickle ``data`` to ``filename``."""
    with open(filename, "wb") as f:
        pickle.dump(data, f, protocol=4)


def save_list(l, outfile):
    """Write one item per line."""
    with open(outfile, "w") as f:
        for item in l:
            f.write("%s\n" % (item,))


def exclude_pattern(f):
    """True for hidden/system entries that should be skipped."""
    return f.startswith(".") or f.endswith("~")


def list_dirs(path):
    """Immediate subdirectories of ``path`` (hidden ones excluded)."""
    return sorted(
        d for d in os.listdir(path)
        if os.path.isdir(os.path.join(path, d)) and not exclude_pattern(d))


def list_images(path, exts=frozenset(("jpg", "png", "bmp", "jpeg"))):
    """Image files directly under ``path``."""
    return sorted(
        f for f in os.listdir(path)
        if os.path.isfile(os.path.join(path, f)) and not exclude_pattern(f)
        and f.rsplit(".", 1)[-1].lower() in exts)


def list_files(path):
    """All regular files directly under ``path``."""
    return sorted(
        f for f in os.listdir(path)
        if os.path.isfile(os.path.join(path, f))
        and not exclude_pattern(f))


def get_label_set_from_dir(path):
    """{class_subdirectory_name: integer_label} for a class-per-dir
    corpus."""
    return {name: i for i, name in enumerate(list_dirs(path))}


class Label(object):
    """A (label, name) pair with the reference's convert/dump surface."""

    def __init__(self, label, name):
        self.label = int(label)
        self.name = name

    def convert_to_paddle_format(self):
        return [self.label]

    def __hash__(self):
        return hash((self.label, self.name))

    def __eq__(self, other):
        return (isinstance(other, Label) and self.label == other.label
                and self.name == other.name)

    def __repr__(self):
        return "Label(%d, %r)" % (self.label, self.name)


class Dataset(object):
    """A list of samples, each ``(data_items..., label)``; knows how to
    shuffle and persist itself in block files."""

    def __init__(self, data, keys):
        self.data = list(data)
        self.keys = list(keys)

    def check_valid(self):
        for item in self.data:
            if len(item) != len(self.keys):
                raise ValueError(
                    "sample arity %d != key arity %d"
                    % (len(item), len(self.keys)))
        return True

    def permute(self, key_id=None, num_per_batch=None, seed=0):
        """Shuffle samples (the reference's class-balancing permute
        degenerates to a seeded shuffle for the dense pipeline)."""
        rng = random.Random(seed)
        rng.shuffle(self.data)
        return self

    def __len__(self):
        return len(self.data)


class DataBatcher(object):
    """Split a Dataset into fixed-size blocks and save each block with
    save_file — the reference's batch-file layout readers stream."""

    def __init__(self, train_data, test_data, label_set):
        self.train_data = train_data
        self.test_data = test_data
        self.label_set = label_set
        self.num_per_batch = 1024

    def create_batches_and_list(self, output_path, train_list_name,
                                test_list_name, label_set_name):
        train_files = self._save_blocks(self.train_data, output_path,
                                        "train")
        test_files = self._save_blocks(self.test_data, output_path, "test")
        save_list(train_files, os.path.join(output_path, train_list_name))
        save_list(test_files, os.path.join(output_path, test_list_name))
        save_file(self.label_set, os.path.join(output_path,
                                               label_set_name))
        return train_files, test_files

    def _save_blocks(self, dataset, output_path, prefix):
        names = []
        for i in range(0, len(dataset.data), self.num_per_batch):
            name = "%s_batch_%03d" % (prefix, i // self.num_per_batch)
            save_file({"keys": dataset.keys,
                       "data": dataset.data[i:i + self.num_per_batch]},
                      os.path.join(output_path, name))
            names.append(name)
        return names


class DatasetCreater(object):
    """Base corpus builder: subclasses implement create_dataset_from_dir
    (ref DatasetCreater.create_dataset_from_list/dir)."""

    def __init__(self, data_path):
        self.data_path = data_path
        self.train_dir_name = "train"
        self.test_dir_name = "test"
        self.batch_dir_name = "batches"
        self.train_list_name = "train.list"
        self.test_list_name = "test.list"
        self.label_set_name = "labels.pkl"
        self.num_per_batch = 1024
        self.overwrite = False

    def create_dataset_from_dir(self, path, label_set=None):
        """Build a Dataset from one split directory. ``label_set`` is
        the train-split {class: label} mapping — use it (when given) so
        every split numbers classes identically."""
        raise NotImplementedError(
            "subclass DatasetCreater and build a Dataset from %r" % path)

    def create_batches(self):
        train_path = os.path.join(self.data_path, self.train_dir_name)
        test_path = os.path.join(self.data_path, self.test_dir_name)
        out_path = os.path.join(self.data_path, self.batch_dir_name)
        if os.path.exists(out_path) and not self.overwrite:
            return out_path
        os.makedirs(out_path, exist_ok=True)
        label_set = get_label_set_from_dir(train_path)
        train = self.create_dataset_from_dir(train_path, label_set)
        test = self.create_dataset_from_dir(test_path, label_set)
        batcher = DataBatcher(train, test, label_set)
        batcher.num_per_batch = self.num_per_batch
        batcher.create_batches_and_list(out_path, self.train_list_name,
                                        self.test_list_name,
                                        self.label_set_name)
        return out_path
