"""fluid.data parity (ref python/paddle/fluid/data.py).

Unlike ``layers.data`` (which prepends an implicit -1 batch dimension),
``fluid.data`` declares the FULL shape; ``None`` dims mean any size.
Feeds are shape/dtype-checked at run time by the Executor's feed
boundary (executor.py _convert_feed's named errors — the behavior this
API was introduced for).
"""
from .layers import io as _io

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0):
    # layers.io.data defaults stop_gradient=True (feed vars)
    return _io.data(name, [(-1 if s is None else int(s)) for s in shape],
                    dtype=dtype, append_batch_size=False,
                    lod_level=lod_level)
