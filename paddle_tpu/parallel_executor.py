"""ParallelExecutor API shim.

Reference parity: python/paddle/fluid/parallel_executor.py. The reference
class owns per-device scopes + NCCL; here it is a thin veneer over
CompiledProgram/pjit — kept so fluid training scripts run unchanged.
"""
from .framework.compiler import BuildStrategy, CompiledProgram, \
    ExecutionStrategy
from .framework.executor import Executor
from .framework.program import default_main_program


class ParallelExecutor(object):
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program or default_main_program()
        self._compiled = CompiledProgram(
            self._program, build_strategy).with_data_parallel(
                loss_name=loss_name, exec_strategy=exec_strategy)
        self._exe = Executor()
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._compiled, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)

    @property
    def device_count(self):
        import jax
        return len(jax.devices())
