"""Module-path alias for fluid.inferencer (ref
python/paddle/fluid/inferencer.py)."""
from .contrib.inferencer import Inferencer  # noqa: F401

__all__ = ["Inferencer"]
