"""Module-path alias for fluid.backward (ref
python/paddle/fluid/backward.py): graph-level autodiff entry points live
in framework/backward.py; this name exists so ``import
paddle_tpu.backward`` ports 1:1."""
from .framework.backward import append_backward, gradients, \
    calc_gradient_in_block  # noqa: F401

__all__ = ["append_backward", "gradients"]
