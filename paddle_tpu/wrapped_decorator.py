"""fluid.wrapped_decorator parity (ref
python/paddle/fluid/wrapped_decorator.py) — stdlib-only: functools.wraps
preserves signatures well enough without the `decorator` package."""
import contextlib
import functools

__all__ = ["wrap_decorator", "signature_safe_contextmanager"]


def wrap_decorator(decorator_func):
    @functools.wraps(decorator_func)
    def __impl__(func):
        wrapped = decorator_func(func)
        return functools.wraps(func)(wrapped)
    return __impl__


signature_safe_contextmanager = wrap_decorator(contextlib.contextmanager)
