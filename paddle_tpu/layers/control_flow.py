"""Control-flow layers: cond / while_loop / case / switch_case.

Reference parity: python/paddle/fluid/layers/control_flow.py. Sub-blocks are
built at layer time (ops recorded into child Blocks) and traced into
lax.cond / lax.while_loop at executor compile time — on-device control flow.
"""
import contextlib

from ..layer_helper import LayerHelper
from ..framework.program import Variable, default_main_program
from ..framework import unique_name


def _compare(x, y, op_type, cond=None):
    from . import tensor as tensor_layers
    helper = LayerHelper(op_type)
    if not isinstance(y, Variable):
        y = tensor_layers.fill_constant([1], x.dtype, float(y))
    out = helper.create_variable_for_type_inference("bool", x.shape)
    helper.append_op(op_type, inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    out.stop_gradient = True
    if cond is not None:
        # fluid's out-parameter form: write the result onto `cond` —
        # how While bodies refresh their carried condition
        current = default_main_program().current_block()
        current.append_op("assign", inputs={"X": [out.name]},
                          outputs={"Out": [cond.name]})
        return cond
    return out


def less_than(x, y, force_cpu=None, cond=None):
    return _compare(x, y, "less_than", cond=cond)


def less_equal(x, y, cond=None):
    return _compare(x, y, "less_equal", cond=cond)


def greater_than(x, y, cond=None):
    return _compare(x, y, "greater_than", cond=cond)


def greater_equal(x, y, cond=None):
    return _compare(x, y, "greater_equal", cond=cond)


def equal(x, y, cond=None):
    return _compare(x, y, "equal", cond=cond)


def not_equal(x, y, cond=None):
    return _compare(x, y, "not_equal", cond=cond)


def logical_and(x, y, out=None, name=None):
    return _compare(x, y, "logical_and")


def logical_or(x, y, out=None, name=None):
    return _compare(x, y, "logical_or")


def logical_xor(x, y, out=None, name=None):
    return _compare(x, y, "logical_xor")


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not")
    out = helper.create_variable_for_type_inference("bool", x.shape)
    helper.append_op("logical_not", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("increment", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"step": float(value)})
    return out


def _build_subblock(fn, program):
    """Run fn() with a fresh child block current; return (block, outputs)."""
    block = program._create_block()
    try:
        outs = fn() if fn is not None else None
    finally:
        program._rollback()
    if outs is None:
        outs = []
    if isinstance(outs, Variable):
        outs = [outs]
    return block, list(outs)


def _collect_captures(blocks_and_outs, bound_names):
    """Outer-scope names the sub-blocks read (read-before-written, plus
    returned-but-never-defined), beyond `bound_names`. Listing these as
    explicit op inputs is what lets gradients flow through control flow:
    jax.vjp differentiates w.r.t. declared inputs, not closures."""
    captured, seen = [], set(bound_names)
    for block, out_names in blocks_and_outs:
        defined = set(bound_names)
        for op in block.ops:
            for n in op.input_names():
                if n not in defined and n not in seen and \
                        not n.startswith("@"):
                    captured.append(n)
                    seen.add(n)
            defined.update(op.output_names())
        for n in out_names:
            if n not in defined and n not in seen and not n.startswith("@"):
                captured.append(n)
                seen.add(n)
    return captured


def cond(pred, true_fn=None, false_fn=None, name=None):
    """layers.cond(pred, true_fn, false_fn) -> vars with matching structure.

    Both branches run as traced lax.cond branches on device. Differentiable:
    outer vars the branches read are lifted to explicit `Captures` inputs,
    so append_backward pairs this op with a vjp like any other (reference:
    conditional_block_grad_op in operators/controlflow).
    """
    helper = LayerHelper("cond", name=name)
    program = default_main_program()
    true_block, true_outs = _build_subblock(true_fn, program)
    false_block, false_outs = _build_subblock(false_fn, program)
    if len(true_outs) != len(false_outs):
        raise ValueError(
            "cond branches returned different numbers of outputs: %d vs %d"
            % (len(true_outs), len(false_outs)))
    captures = _collect_captures(
        [(true_block, [v.name for v in true_outs]),
         (false_block, [v.name for v in false_outs])], bound_names=())
    outs = [helper.create_variable_for_type_inference(v.dtype, v.shape)
            for v in true_outs]
    helper.append_op(
        "cond", inputs={"Cond": [pred.name], "Captures": captures},
        outputs={"Out": [o.name for o in outs]},
        attrs={"true_block": true_block.idx, "false_block": false_block.idx,
               "true_out_names": [v.name for v in true_outs],
               "false_out_names": [v.name for v in false_outs],
               "capture_names": captures})
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else outs


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None,
               maximum_trip_count=None):
    """layers.while_loop — on-device loop.

    Without `maximum_trip_count`: lax.while_loop (dynamic trip count;
    forward-only — XLA cannot reverse-differentiate an unbounded loop).
    With `maximum_trip_count=N`: a bounded differentiable form — lax.scan of
    N steps where iterations past the cond turning false are masked out
    (jnp.where keeps the old carry). Gradients then flow to both the initial
    loop values and any captured outer vars (reference: while_grad_op in
    operators/controlflow/while_op.cc; the bound replaces the reference's
    per-iteration activation stack, which has no static-shape TPU form).
    """
    helper = LayerHelper("while_loop", name=name)
    program = default_main_program()

    cond_block = program._create_block()
    try:
        pred = cond_fn(*loop_vars)
    finally:
        program._rollback()

    body_block = program._create_block()
    try:
        new_vars = body_fn(*loop_vars)
    finally:
        program._rollback()
    if isinstance(new_vars, Variable):
        new_vars = [new_vars]
    new_vars = list(new_vars)
    if len(new_vars) != len(loop_vars):
        raise ValueError("while_loop body must return as many vars as "
                         "loop_vars")
    # the body must write back into the loop var names; emit assigns
    for lv, nv in zip(loop_vars, new_vars):
        if nv.name != lv.name:
            body_block.append_op("assign", inputs={"X": [nv.name]},
                                 outputs={"Out": [lv.name]})

    loop_names = [v.name for v in loop_vars]
    captures = _collect_captures(
        [(cond_block, [pred.name]), (body_block, [])],
        bound_names=loop_names)
    outs = [helper.create_variable_for_type_inference(v.dtype, v.shape)
            for v in loop_vars]
    attrs = {"cond_block": cond_block.idx, "body_block": body_block.idx,
             "loop_var_names": loop_names, "cond_out_name": pred.name,
             "capture_names": captures}
    op_type = "while_loop"
    if maximum_trip_count is not None:
        op_type = "bounded_while"
        attrs["max_trip_count"] = int(maximum_trip_count)
    helper.append_op(
        op_type,
        inputs={"LoopVars": loop_names, "Captures": captures},
        outputs={"Out": [o.name for o in outs]},
        attrs=attrs)
    return outs


def case(pred_fn_pairs, default=None, name=None):
    """Reference layers.case — nested cond chain."""
    def build(pairs):
        pred, fn = pairs[0]
        rest = pairs[1:]
        if not rest:
            if default is None:
                return cond(pred, fn, fn)
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(rest))
    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    pairs = [(equal(branch_index, float(i)), fn)
             for i, fn in (branch_fns.items()
                           if isinstance(branch_fns, dict)
                           else enumerate(branch_fns))]
    return case(pairs, default=default, name=name)


def piecewise_select(step, boundaries, values, dtype="float32"):
    """select values[i] where boundaries[i-1] <= step < boundaries[i] —
    the TPU-friendly lowering of the reference's Switch construct
    (a chain of `where` selects, fully on device)."""
    from . import tensor as tensor_layers
    from .nn import where
    out = tensor_layers.fill_constant([1], dtype, values[-1])
    for b, v in reversed(list(zip(boundaries, values[:-1]))):
        v_var = tensor_layers.fill_constant([1], dtype, v)
        out = where(less_than(step, float(b)), v_var, out)
    return out


def recompute_segment(fn, inputs, name=None):
    """Run fn(*inputs) inside a rematerialized segment: activations inside
    the segment are not kept for backward — XLA recomputes them
    (jax.checkpoint). The segment's parameter reads are auto-detected as
    captures so gradients still flow to them.

    Reference parity: RecomputeOptimizer/_set_checkpoints; here recompute is
    per-segment and composes with any optimizer."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("recompute", name=name)
    program = default_main_program()
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    inputs = list(inputs)

    block = program._create_block()
    try:
        outs = fn(*inputs)
    finally:
        program._rollback()
    if isinstance(outs, Variable):
        outs = [outs]
    outs = list(outs)

    # captures: names read before written inside the segment, beyond inputs
    input_names = {v.name for v in inputs}
    captured = _collect_captures([(block, [v.name for v in outs])],
                                 bound_names=input_names)
    parent = program.current_block()
    cap_vars = []
    for n in captured:
        v = parent._find_var_recursive(n)
        if v is None:
            v = block._find_var_recursive(n)
        cap_vars.append(v)

    in_all = inputs + [v for v in cap_vars if v is not None]
    out_vars = [helper.create_variable_for_type_inference(v.dtype, v.shape)
                for v in outs]
    helper.append_op(
        "remat_block",
        inputs={"In": [v.name for v in in_all]},
        outputs={"Out": [v.name for v in out_vars]},
        attrs={"sub_block": block.idx,
               "in_names": [v.name for v in in_all],
               "out_names": [v.name for v in outs]})
    if len(out_vars) == 1:
        return out_vars[0]
    return out_vars


# ---------------------------------------------------------------------------
# fluid-style control-flow classes (reference layers/control_flow.py:
# While, Switch, StaticRNN, DynamicRNN, IfElse + LoDTensorArray ops).
# TPU-native: blocks are captured as sub-blocks and lowered onto the same
# lax.while_loop / lax.scan / where-select kernels the functional API uses.
# ---------------------------------------------------------------------------

class While(object):
    """fluid.layers.While: the body block runs until the carried cond var
    turns false (ref control_flow.py class While / while_op.cc). The body
    must update `cond` (e.g. layers.less_than(i, n, cond=cond)); every
    outer var the body assigns becomes a loop-carried value.

    Forward-only (lax.while_loop; dynamic trip count — same gradient
    restriction as layers.while_loop without maximum_trip_count)."""

    def __init__(self, cond, is_test=False, name=None):
        if str(cond.dtype) not in ("bool",):
            raise TypeError("While cond must be a bool Variable")
        self._cond = cond
        self._helper = LayerHelper("while", name=name)

    @contextlib.contextmanager
    def block(self):
        program = default_main_program()
        parent = program.current_block()
        body = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        # loop vars: outer vars the body writes (reads of stale values are
        # loop-carried too), cond first
        written = []
        for op in body.ops:
            for n in op.output_names():
                if n in body.vars:       # temp created inside the body
                    continue
                if n not in written and \
                        parent._find_var_recursive(n) is not None:
                    written.append(n)
        loop_names = [self._cond.name] + \
            [n for n in written if n != self._cond.name]
        cond_block = program._create_block()
        program._rollback()              # empty: pred is the carried var
        captures = _collect_captures(
            [(cond_block, [self._cond.name]), (body, [])],
            bound_names=loop_names)
        outs = []
        for n in loop_names:
            v = parent._find_var_recursive(n)
            outs.append(self._helper.create_variable_for_type_inference(
                v.dtype, v.shape))
        self._helper.append_op(
            "while_loop",
            inputs={"LoopVars": loop_names, "Captures": captures},
            outputs={"Out": [o.name for o in outs]},
            attrs={"cond_block": cond_block.idx, "body_block": body.idx,
                   "loop_var_names": loop_names,
                   "cond_out_name": self._cond.name,
                   "capture_names": captures})
        # write final values back onto the outer names
        blk = program.current_block()
        for n, o in zip(loop_names, outs):
            blk.append_op("assign", inputs={"X": [o.name]},
                          outputs={"Out": [n]})


class Switch(object):
    """fluid.layers.Switch: the first case whose condition holds executes;
    the optional default runs when none do (ref control_flow.py Switch,
    the lr-scheduler idiom). Cases communicate via assigns to outer vars;
    lowering is a reversed chain of `cond` ops selecting those vars."""

    def __init__(self, name=None):
        self._helper = LayerHelper("switch", name=name)
        self._cases = []          # (cond var or None, block)
        self._got_default = False

    def __enter__(self):
        return self

    @contextlib.contextmanager
    def case(self, condition):
        if self._got_default:
            raise ValueError("case() after default()")
        program = default_main_program()
        blk = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        self._cases.append((condition, blk))

    @contextlib.contextmanager
    def default(self):
        if self._got_default:
            raise ValueError("there can be at most one default() case "
                             "in a Switch")
        program = default_main_program()
        blk = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        self._cases.append((None, blk))
        self._got_default = True

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        program = default_main_program()
        parent = program.current_block()
        # all outer vars any case assigns
        written = []
        for _, blk in self._cases:
            for op in blk.ops:
                for n in op.output_names():
                    if n not in blk.vars and n not in written and \
                            parent._find_var_recursive(n) is not None:
                        written.append(n)
        if not written:
            return False
        # build the else-chain back to front; start from current values
        else_block = program._create_block()
        program._rollback()              # empty block: passthrough
        else_names = list(written)
        else_idx = else_block.idx
        chain = [c for c in self._cases]
        default = None
        if chain and chain[-1][0] is None:
            default = chain.pop()[1]
            else_idx = default.idx
        final_outs = None
        if not chain:
            if default is None:
                return False
            # default-only Switch: select the default block unconditionally
            from . import tensor as T
            always = T.fill_constant([1], "bool", True)
            chain = [(always, default)]
            else_block2 = program._create_block()
            program._rollback()
            else_idx = else_block2.idx
        for cond_var, blk in reversed(chain):
            captures = _collect_captures(
                [(blk, written), (program.block(else_idx), else_names)],
                bound_names=())
            outs = [self._helper.create_variable_for_type_inference(
                parent._find_var_recursive(n).dtype,
                parent._find_var_recursive(n).shape) for n in written]
            self._helper.append_op(
                "cond",
                inputs={"Cond": [cond_var.name], "Captures": captures},
                outputs={"Out": [o.name for o in outs]},
                attrs={"true_block": blk.idx,
                       "false_block": else_idx,
                       "true_out_names": written,
                       "false_out_names": else_names,
                       "capture_names": captures})
            # this cond's outputs become the next (earlier) case's "else"
            passthrough = program._create_block()
            program._rollback()
            for n, o in zip(written, outs):
                passthrough.append_op("assign", inputs={"X": [o.name]},
                                      outputs={"Out": [n]})
            else_idx = passthrough.idx
            else_names = list(written)
            final_outs = outs
        blk = program.current_block()
        for n, o in zip(written, final_outs):
            blk.append_op("assign", inputs={"X": [o.name]},
                          outputs={"Out": [n]})
        return False


class StaticRNN(object):
    """fluid.layers.StaticRNN (ref control_flow.py StaticRNN /
    recurrent_op.cc): record one step's ops in a sub-block, run it as a
    differentiable lax.scan over time-major inputs (T, B, ...)."""

    def __init__(self, name=None):
        self._helper = LayerHelper("static_rnn", name=name)
        self._block = None
        self._seq = []      # (placeholder, outer seq var)
        self._mems = []     # dicts: ph, init(Variable|None), shape, value,
                            #        batch_ref, new (Variable)
        self._outs = []     # step-local output vars

    @contextlib.contextmanager
    def step(self):
        program = default_main_program()
        self._program = program
        self._block = program._create_block()
        try:
            yield
        finally:
            program._rollback()

    def _require_block(self):
        if self._block is None:
            raise RuntimeError("call inside `with rnn.step():`")

    def step_input(self, x):
        self._require_block()
        ph = self._block.create_var(
            name=unique_name.generate("rnn_step_in"),
            shape=tuple(x.shape[1:]) if x.shape else None, dtype=x.dtype)
        self._seq.append((ph, x))
        return ph

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1,
               dtype=None):
        self._require_block()
        if init is not None:
            mshape, dtype = tuple(init.shape), init.dtype
        else:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs init= or shape=+batch_ref=")
            mshape = tuple(batch_ref.shape[0] if s in (None, -1) else s
                           for s in shape)
            dtype = dtype or batch_ref.dtype
        ph = self._block.create_var(
            name=unique_name.generate("rnn_mem"), shape=mshape, dtype=dtype)
        self._mems.append({"ph": ph, "init": init, "shape": mshape,
                           "value": float(init_value), "new": None})
        return ph

    def update_memory(self, mem, new):
        for m in self._mems:
            if m["ph"].name == mem.name:
                m["new"] = new
                return
        raise ValueError("update_memory: %r is not a memory" % mem.name)

    def step_output(self, o):
        self._require_block()
        self._outs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        from . import tensor as T
        if any(m["new"] is None for m in self._mems):
            raise ValueError("every memory needs update_memory()")
        inits = []
        for m in self._mems:
            if m["init"] is not None:
                inits.append(m["init"])
            else:
                inits.append(T.fill_constant(list(m["shape"]),
                                             str(m["ph"].dtype), m["value"]))
        seq_names = [ph.name for ph, _ in self._seq]
        carry_names = [m["ph"].name for m in self._mems]
        carry_out = [m["new"].name for m in self._mems]
        out_names = [o.name for o in self._outs]
        captures = _collect_captures(
            [(self._block, carry_out + out_names)],
            bound_names=seq_names + carry_names)
        t = self._seq[0][1].shape[0] if self._seq else None
        seq_outs = [self._helper.create_variable_for_type_inference(
            o.dtype, None if (o.shape is None or t in (None, -1))
            else (t,) + tuple(o.shape)) for o in self._outs]
        finals = [self._helper.create_variable_for_type_inference(
            m["ph"].dtype, m["shape"]) for m in self._mems]
        self._helper.append_op(
            "recurrent_scan",
            inputs={"Seq": [v.name for _, v in self._seq],
                    "Init": [v.name for v in inits],
                    "Extra": captures},
            outputs={"FinalCarry": [f.name for f in finals],
                     "SeqOut": [s.name for s in seq_outs]},
            attrs={"sub_block": self._block.idx,
                   "seq_var_names": seq_names,
                   "carry_var_names": carry_names,
                   "extra_var_names": captures,
                   "carry_out_names": carry_out,
                   "step_out_names": out_names})
        self._finals = finals
        if not seq_outs:
            return None
        return seq_outs[0] if len(seq_outs) == 1 else seq_outs


class DynamicRNN(object):
    """fluid.layers.DynamicRNN on the dense design: batch-major (B, T, ...)
    input + explicit lengths replace the LoD (ref control_flow.py
    DynamicRNN). Steps past a row's length keep the previous memory and
    emit zeros — the masked-scan equivalent of the reference's
    shrink-at-each-step execution."""

    def __init__(self, name=None):
        self._rnn = StaticRNN(name=name)
        self._lengths = None
        self._mask_ph = None
        self._first_ph = None
        self._step_idx = 0

    def block(self):
        return self._rnn.step()

    def step_input(self, input, lengths=None):
        from .nn import transpose
        if lengths is not None:
            self._lengths = lengths
        # batch-major -> time-major for the scan
        perm = list(range(len(input.shape)))
        perm[0], perm[1] = 1, 0
        # transpose must happen OUTSIDE the step block: stash and emit in
        # the parent via the recorded outer var
        program = default_main_program()
        program._rollback()
        try:
            tm = transpose(input, perm)
            if self._lengths is not None and self._mask_ph is None:
                from .nn import sequence_mask, cast, unsqueeze
                m = sequence_mask(self._lengths, maxlen=input.shape[1],
                                  dtype="float32")       # (B, T)
                m = transpose(m, [1, 0])                  # (T, B)
                m = unsqueeze(m, [2])                     # (T, B, 1)
                self._mask = m
        finally:
            program.current_block_idx = self._rnn._block.idx
        ph = self._rnn.step_input(tm)
        if self._first_ph is None:
            self._first_ph = ph
        if self._lengths is not None and self._mask_ph is None:
            self._mask_ph = self._rnn.step_input(self._mask)
        return ph

    def _mask_for(self, value):
        """Per-step keep-mask shaped/cast to broadcast against *value*:
        mask_ph is (B, 1); values may be rank 1..N."""
        from .nn import cast, unsqueeze, reshape
        m = self._mask_ph
        rank = len(value.shape or ())
        if rank <= 1:
            m = reshape(m, [-1])
        elif rank > 2:
            m = unsqueeze(m, list(range(2, rank)))
        if value.dtype != m.dtype:
            m = cast(m, value.dtype)
        return m

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32", batch_ref=None):
        if init is None:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            if batch_ref is None:
                if self._first_ph is None:
                    raise ValueError(
                        "DynamicRNN.memory(shape=...): call step_input() "
                        "first so the batch size is known")
                batch_ref = self._first_ph
                # fluid semantics: shape is per-sample; batch prepended
                shape = [-1] + list(shape)
            return self._rnn.memory(shape=shape, batch_ref=batch_ref,
                                    init_value=value, dtype=dtype)
        return self._rnn.memory(init=init, init_value=value)

    def update_memory(self, ex_mem, new_mem):
        if self._mask_ph is not None:
            from .nn import elementwise_mul, elementwise_add, scale
            m = self._mask_for(new_mem)
            keep = scale(m, scale=-1.0, bias=1.0)
            new_mem = elementwise_add(elementwise_mul(new_mem, m),
                                      elementwise_mul(ex_mem, keep))
        self._rnn.update_memory(ex_mem, new_mem)

    def output(self, *outputs):
        if self._mask_ph is not None:
            from .nn import elementwise_mul
            outputs = [elementwise_mul(o, self._mask_for(o))
                       for o in outputs]
        self._rnn.output(*outputs)

    def __call__(self):
        from .nn import transpose
        outs = self._rnn()
        if outs is None:
            return None
        single = not isinstance(outs, list)
        outs = [outs] if single else outs
        res = []
        for o in outs:
            perm = list(range(len(o.shape) if o.shape else 3))
            perm[0], perm[1] = 1, 0
            res.append(transpose(o, perm))   # back to batch-major
        return res[0] if single else res

    def final_states(self):
        """Final memory values after the scan, in memory() order.  With
        lengths, update_memory freezes each row's carry past its valid
        prefix, so these ARE the states at t = len-1 (used by
        layers.rnn for its final_states return)."""
        finals = getattr(self._rnn, "_finals", None)
        if finals is None:
            raise ValueError("final_states() is available after the "
                             "DynamicRNN has been called")
        return list(finals)


def is_empty(x, cond=None):
    """Static element-count test (ref control_flow.py is_empty). Dynamic
    (-1) dims are unknown at build time and rejected rather than guessed."""
    from . import tensor as T
    n = 1
    for s in (x.shape or ()):
        if s in (None, -1):
            raise ValueError(
                "is_empty needs fully static shapes on TPU; %r has a "
                "dynamic dim" % getattr(x, "name", x))
        n *= s
    out = T.fill_constant([1], "bool", bool(n == 0))
    if cond is not None:
        current = default_main_program().current_block()
        current.append_op("assign", inputs={"X": [out.name]},
                          outputs={"Out": [cond.name]})
        return cond
    return out


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print that stays in the compiled step (ref
    control_flow.py Print / print_op: here jax.debug.print, gradients pass
    through untouched)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    input.shape)
    helper.append_op("print", inputs={"In": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"message": message or input.name,
                            "summarize": int(summarize)})
    return out


# ---- bounded TensorArray (build-time list design) ------------------------

class _TensorArray(list):
    """LoDTensorArray stand-in: a BUILD-TIME list of Variables. The
    dominant static-graph uses (collecting per-step outputs, beam-search
    assembly in python loops) index with python ints; dynamic Variable
    indices inside While have no static-shape equivalent and raise."""
    pass


def create_array(dtype):
    return _TensorArray()


def _static_index(i):
    if hasattr(i, "name"):
        raise NotImplementedError(
            "TensorArray with a Variable index inside device loops has no "
            "static-shape TPU form; use layers.while_loop loop_vars or "
            "StaticRNN memories instead")
    return int(i)


def array_write(x, i, array=None):
    """ref control_flow.py array_write (python-int index)."""
    i = _static_index(i)
    if array is None:
        array = _TensorArray()
    while len(array) <= i:
        array.append(None)
    array[i] = x
    return array


def array_read(array, i):
    v = array[_static_index(i)]
    if v is None:
        raise IndexError("array_read at unwritten index")
    return v


def array_length(array):
    from . import tensor as T
    return T.fill_constant([1], "int64", len(array))


class IfElse(object):
    """fluid.layers.IfElse: rows where cond holds flow through the true
    block, the rest through the false block, outputs merged by row (ref
    control_flow.py IfElse / split_lod_tensor+merge_lod_tensor ops).

    Dense TPU form: BOTH branches compute over the full batch and the
    merge is a per-row where-select on cond — identical results, no
    dynamic row splitting (static shapes; the branch FLOPs are the price
    of SPMD, as with every masked-batch idiom here)."""

    def __init__(self, cond, name=None):
        self._cond = cond                 # (N, 1) bool
        self._helper = LayerHelper("ifelse", name=name)
        self._in_true = None
        self._outs = {True: [], False: []}

    @contextlib.contextmanager
    def true_block(self):
        self._in_true = True
        try:
            yield
        finally:
            self._in_true = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_true = False
        try:
            yield
        finally:
            self._in_true = None

    def input(self, x):
        if self._in_true is None:
            raise RuntimeError("IfElse.input outside a block")
        return x                          # full batch; select happens at ()

    def output(self, *outs):
        if self._in_true is None:
            raise RuntimeError("IfElse.output outside a block")
        self._outs[self._in_true].extend(outs)

    def __call__(self):
        from .nn import where, cast, expand
        t, f = self._outs[True], self._outs[False]
        if len(t) != len(f):
            raise ValueError("IfElse branches produced %d vs %d outputs"
                             % (len(t), len(f)))
        merged = []
        for tv, fv in zip(t, f):
            c = self._cond
            merged.append(where(c, tv, fv))
        return merged


def lod_rank_table(x, level=0, lengths=None):
    """Rank table = row order by descending length (ref
    control_flow.py lod_rank_table). Dense design: the table IS the
    lengths vector; pass it to reorder_lod_tensor_by_rank."""
    if lengths is None:
        raise ValueError("dense design: pass lengths= explicitly")
    return lengths


def reorder_lod_tensor_by_rank(x, rank_table):
    """Reorder rows by descending length (ref
    control_flow.py reorder_lod_tensor_by_rank + reorder_lod_tensor_by_rank
    op — the DynamicRNN sorting step). rank_table: the (N,) lengths."""
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("reorder_by_rank",
                     inputs={"X": [x.name],
                             "RankTable": [rank_table.name]},
                     outputs={"Out": [out.name]})
    return out
