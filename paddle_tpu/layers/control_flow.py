"""Control-flow layers: cond / while_loop / case / switch_case.

Reference parity: python/paddle/fluid/layers/control_flow.py. Sub-blocks are
built at layer time (ops recorded into child Blocks) and traced into
lax.cond / lax.while_loop at executor compile time — on-device control flow.
"""
from ..layer_helper import LayerHelper
from ..framework.program import Variable, default_main_program


def _compare(x, y, op_type):
    from . import tensor as tensor_layers
    helper = LayerHelper(op_type)
    if not isinstance(y, Variable):
        y = tensor_layers.fill_constant([1], x.dtype, float(y))
    out = helper.create_variable_for_type_inference("bool", x.shape)
    helper.append_op(op_type, inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    out.stop_gradient = True
    return out


def less_than(x, y, force_cpu=None, cond=None):
    return _compare(x, y, "less_than")


def less_equal(x, y, cond=None):
    return _compare(x, y, "less_equal")


def greater_than(x, y, cond=None):
    return _compare(x, y, "greater_than")


def greater_equal(x, y, cond=None):
    return _compare(x, y, "greater_equal")


def equal(x, y, cond=None):
    return _compare(x, y, "equal")


def not_equal(x, y, cond=None):
    return _compare(x, y, "not_equal")


def logical_and(x, y, out=None, name=None):
    return _compare(x, y, "logical_and")


def logical_or(x, y, out=None, name=None):
    return _compare(x, y, "logical_or")


def logical_xor(x, y, out=None, name=None):
    return _compare(x, y, "logical_xor")


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not")
    out = helper.create_variable_for_type_inference("bool", x.shape)
    helper.append_op("logical_not", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("increment", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"step": float(value)})
    return out


def _build_subblock(fn, program):
    """Run fn() with a fresh child block current; return (block, outputs)."""
    block = program._create_block()
    try:
        outs = fn() if fn is not None else None
    finally:
        program._rollback()
    if outs is None:
        outs = []
    if isinstance(outs, Variable):
        outs = [outs]
    return block, list(outs)


def _collect_captures(blocks_and_outs, bound_names):
    """Outer-scope names the sub-blocks read (read-before-written, plus
    returned-but-never-defined), beyond `bound_names`. Listing these as
    explicit op inputs is what lets gradients flow through control flow:
    jax.vjp differentiates w.r.t. declared inputs, not closures."""
    captured, seen = [], set(bound_names)
    for block, out_names in blocks_and_outs:
        defined = set(bound_names)
        for op in block.ops:
            for n in op.input_names():
                if n not in defined and n not in seen and \
                        not n.startswith("@"):
                    captured.append(n)
                    seen.add(n)
            defined.update(op.output_names())
        for n in out_names:
            if n not in defined and n not in seen and not n.startswith("@"):
                captured.append(n)
                seen.add(n)
    return captured


def cond(pred, true_fn=None, false_fn=None, name=None):
    """layers.cond(pred, true_fn, false_fn) -> vars with matching structure.

    Both branches run as traced lax.cond branches on device. Differentiable:
    outer vars the branches read are lifted to explicit `Captures` inputs,
    so append_backward pairs this op with a vjp like any other (reference:
    conditional_block_grad_op in operators/controlflow).
    """
    helper = LayerHelper("cond", name=name)
    program = default_main_program()
    true_block, true_outs = _build_subblock(true_fn, program)
    false_block, false_outs = _build_subblock(false_fn, program)
    if len(true_outs) != len(false_outs):
        raise ValueError(
            "cond branches returned different numbers of outputs: %d vs %d"
            % (len(true_outs), len(false_outs)))
    captures = _collect_captures(
        [(true_block, [v.name for v in true_outs]),
         (false_block, [v.name for v in false_outs])], bound_names=())
    outs = [helper.create_variable_for_type_inference(v.dtype, v.shape)
            for v in true_outs]
    helper.append_op(
        "cond", inputs={"Cond": [pred.name], "Captures": captures},
        outputs={"Out": [o.name for o in outs]},
        attrs={"true_block": true_block.idx, "false_block": false_block.idx,
               "true_out_names": [v.name for v in true_outs],
               "false_out_names": [v.name for v in false_outs],
               "capture_names": captures})
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else outs


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None,
               maximum_trip_count=None):
    """layers.while_loop — on-device loop.

    Without `maximum_trip_count`: lax.while_loop (dynamic trip count;
    forward-only — XLA cannot reverse-differentiate an unbounded loop).
    With `maximum_trip_count=N`: a bounded differentiable form — lax.scan of
    N steps where iterations past the cond turning false are masked out
    (jnp.where keeps the old carry). Gradients then flow to both the initial
    loop values and any captured outer vars (reference: while_grad_op in
    operators/controlflow/while_op.cc; the bound replaces the reference's
    per-iteration activation stack, which has no static-shape TPU form).
    """
    helper = LayerHelper("while_loop", name=name)
    program = default_main_program()

    cond_block = program._create_block()
    try:
        pred = cond_fn(*loop_vars)
    finally:
        program._rollback()

    body_block = program._create_block()
    try:
        new_vars = body_fn(*loop_vars)
    finally:
        program._rollback()
    if isinstance(new_vars, Variable):
        new_vars = [new_vars]
    new_vars = list(new_vars)
    if len(new_vars) != len(loop_vars):
        raise ValueError("while_loop body must return as many vars as "
                         "loop_vars")
    # the body must write back into the loop var names; emit assigns
    for lv, nv in zip(loop_vars, new_vars):
        if nv.name != lv.name:
            body_block.append_op("assign", inputs={"X": [nv.name]},
                                 outputs={"Out": [lv.name]})

    loop_names = [v.name for v in loop_vars]
    captures = _collect_captures(
        [(cond_block, [pred.name]), (body_block, [])],
        bound_names=loop_names)
    outs = [helper.create_variable_for_type_inference(v.dtype, v.shape)
            for v in loop_vars]
    attrs = {"cond_block": cond_block.idx, "body_block": body_block.idx,
             "loop_var_names": loop_names, "cond_out_name": pred.name,
             "capture_names": captures}
    op_type = "while_loop"
    if maximum_trip_count is not None:
        op_type = "bounded_while"
        attrs["max_trip_count"] = int(maximum_trip_count)
    helper.append_op(
        op_type,
        inputs={"LoopVars": loop_names, "Captures": captures},
        outputs={"Out": [o.name for o in outs]},
        attrs=attrs)
    return outs


def case(pred_fn_pairs, default=None, name=None):
    """Reference layers.case — nested cond chain."""
    def build(pairs):
        pred, fn = pairs[0]
        rest = pairs[1:]
        if not rest:
            if default is None:
                return cond(pred, fn, fn)
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(rest))
    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    pairs = [(equal(branch_index, float(i)), fn)
             for i, fn in (branch_fns.items()
                           if isinstance(branch_fns, dict)
                           else enumerate(branch_fns))]
    return case(pairs, default=default, name=name)


def piecewise_select(step, boundaries, values, dtype="float32"):
    """select values[i] where boundaries[i-1] <= step < boundaries[i] —
    the TPU-friendly lowering of the reference's Switch construct
    (a chain of `where` selects, fully on device)."""
    from . import tensor as tensor_layers
    from .nn import where
    out = tensor_layers.fill_constant([1], dtype, values[-1])
    for b, v in reversed(list(zip(boundaries, values[:-1]))):
        v_var = tensor_layers.fill_constant([1], dtype, v)
        out = where(less_than(step, float(b)), v_var, out)
    return out


def recompute_segment(fn, inputs, name=None):
    """Run fn(*inputs) inside a rematerialized segment: activations inside
    the segment are not kept for backward — XLA recomputes them
    (jax.checkpoint). The segment's parameter reads are auto-detected as
    captures so gradients still flow to them.

    Reference parity: RecomputeOptimizer/_set_checkpoints; here recompute is
    per-segment and composes with any optimizer."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("recompute", name=name)
    program = default_main_program()
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    inputs = list(inputs)

    block = program._create_block()
    try:
        outs = fn(*inputs)
    finally:
        program._rollback()
    if isinstance(outs, Variable):
        outs = [outs]
    outs = list(outs)

    # captures: names read before written inside the segment, beyond inputs
    input_names = {v.name for v in inputs}
    captured = _collect_captures([(block, [v.name for v in outs])],
                                 bound_names=input_names)
    parent = program.current_block()
    cap_vars = []
    for n in captured:
        v = parent._find_var_recursive(n)
        if v is None:
            v = block._find_var_recursive(n)
        cap_vars.append(v)

    in_all = inputs + [v for v in cap_vars if v is not None]
    out_vars = [helper.create_variable_for_type_inference(v.dtype, v.shape)
                for v in outs]
    helper.append_op(
        "remat_block",
        inputs={"In": [v.name for v in in_all]},
        outputs={"Out": [v.name for v in out_vars]},
        attrs={"sub_block": block.idx,
               "in_names": [v.name for v in in_all],
               "out_names": [v.name for v in outs]})
    if len(out_vars) == 1:
        return out_vars[0]
    return out_vars
