"""Sequence layers — dense/masked TPU design.

Reference parity: python/paddle/fluid/layers/sequence_lod.py +
operators/sequence_ops/*. The reference represents ragged batches with LoD
metadata; that is hostile to XLA's static shapes, so the TPU-native design is
(batch, max_len, ...) dense tensors + explicit length vectors, with masks
derived via sequence_mask (the standard padded-batch idiom; reference
sequence semantics are reproduced on top of it).
"""
from .nn import (sequence_mask, elementwise_mul, reduce_sum, reduce_max,
                 elementwise_div, unsqueeze, expand, softmax)
from . import tensor as tensor_layers


def sequence_pool(input, pool_type, lengths=None):
    """input: (N, T, D) dense; lengths: (N,) int — replaces LoD.
    pool_type: sum | average | max | last | first."""
    if lengths is None:
        if pool_type == "sum":
            return reduce_sum(input, dim=1)
        if pool_type in ("average", "mean"):
            from .nn import reduce_mean
            return reduce_mean(input, dim=1)
        if pool_type == "max":
            return reduce_max(input, dim=1)
    mask = sequence_mask(lengths, maxlen=input.shape[1], dtype=input.dtype)
    mask3 = unsqueeze(mask, [2])
    masked = elementwise_mul(input, mask3)
    if pool_type == "sum":
        return reduce_sum(masked, dim=1)
    if pool_type in ("average", "mean"):
        denom = reduce_sum(mask3, dim=1)
        return elementwise_div(reduce_sum(masked, dim=1), denom)
    if pool_type == "max":
        neg = (mask3 + (-1.0)) * 1e30
        return reduce_max(masked + neg, dim=1)
    raise ValueError("unsupported pool_type %r" % pool_type)


def sequence_softmax(input, lengths=None, axis=1):
    if lengths is None:
        return softmax(input, axis=axis)
    mask = sequence_mask(lengths, maxlen=input.shape[axis],
                         dtype=input.dtype)
    bias = (mask + (-1.0)) * 1e30
    return softmax(input + bias, axis=axis)


def sequence_expand(x, y, ref_level=-1):
    raise NotImplementedError(
        "LoD sequence_expand: use dense broadcast/expand on TPU")


def sequence_concat(input, name=None):
    from .tensor import concat
    return concat(input, axis=1)


def sequence_first_step(input):
    from .nn import slice as slice_layer, squeeze
    s = slice_layer(input, axes=[1], starts=[0], ends=[1])
    return squeeze(s, axes=[1])


def sequence_last_step(input, lengths=None):
    from .nn import slice as slice_layer, squeeze, gather_nd
    if lengths is None:
        s = slice_layer(input, axes=[1], starts=[-1],
                        ends=[input.shape[1] + 1])
        return squeeze(s, axes=[1])
    # gather per-row last valid step
    from . import tensor as T
    import numpy as np
    raise NotImplementedError(
        "length-aware last step: compose with gather_nd on (row, len-1)")


def sequence_reverse(x, name=None):
    from .tensor import reverse
    return reverse(x, axis=[1])


def sequence_pad(x, pad_value, maxlen=None, name=None):
    # dense representation is already padded
    return x, None


def sequence_unpad(x, length, name=None):
    return x
