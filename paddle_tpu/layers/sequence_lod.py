"""Sequence layers — dense/masked TPU design.

Reference parity: python/paddle/fluid/layers/sequence_lod.py +
operators/sequence_ops/*. The reference represents ragged batches with LoD
metadata; that is hostile to XLA's static shapes, so the TPU-native design is
(batch, max_len, ...) dense tensors + explicit length vectors, with masks
derived via sequence_mask (the standard padded-batch idiom; reference
sequence semantics are reproduced on top of it).
"""
from ..layer_helper import LayerHelper
from .nn import (sequence_mask, elementwise_mul, reduce_sum, reduce_max,
                 elementwise_div, unsqueeze, expand, softmax)
from . import tensor as tensor_layers

__all__ = [
    "sequence_pool", "sequence_softmax", "sequence_expand",
    "sequence_expand_as", "sequence_concat", "sequence_first_step",
    "sequence_last_step", "sequence_reverse", "sequence_pad",
    "sequence_unpad", "sequence_erase", "sequence_enumerate",
    "sequence_slice", "sequence_reshape", "sequence_conv",
    "sequence_scatter",
]


def sequence_pool(input, pool_type, lengths=None):
    """input: (N, T, D) dense; lengths: (N,) int — replaces LoD.
    pool_type: sum | average | max | last | first."""
    if pool_type == "first":
        return sequence_first_step(input)
    if pool_type == "last":
        return sequence_last_step(input, lengths)
    if lengths is None:
        if pool_type == "sum":
            return reduce_sum(input, dim=1)
        if pool_type in ("average", "mean"):
            from .nn import reduce_mean
            return reduce_mean(input, dim=1)
        if pool_type == "max":
            return reduce_max(input, dim=1)
        raise ValueError("unsupported pool_type %r" % pool_type)
    mask = sequence_mask(lengths, maxlen=input.shape[1], dtype=input.dtype)
    mask3 = unsqueeze(mask, [2])
    masked = elementwise_mul(input, mask3)
    if pool_type == "sum":
        return reduce_sum(masked, dim=1)
    if pool_type in ("average", "mean"):
        denom = reduce_sum(mask3, dim=1)
        return elementwise_div(reduce_sum(masked, dim=1), denom)
    if pool_type == "max":
        neg = (mask3 + (-1.0)) * 1e30
        return reduce_max(masked + neg, dim=1)
    raise ValueError("unsupported pool_type %r" % pool_type)


def sequence_softmax(input, lengths=None, axis=1):
    if lengths is None:
        return softmax(input, axis=axis)
    mask = sequence_mask(lengths, maxlen=input.shape[axis],
                         dtype=input.dtype)
    bias = (mask + (-1.0)) * 1e30
    return softmax(input + bias, axis=axis)


def _seq_op(op_type, inputs, n_out=1, dtypes=None, attrs=None, name=None):
    helper = LayerHelper(op_type, name=name)
    first = inputs["X"][0]
    dtypes = dtypes or [first.dtype] * n_out
    outs = [helper.create_variable_for_type_inference(dt) for dt in dtypes]
    slots = ["Out", "OutLength"] if n_out == 2 else ["Out"]
    helper.append_op(op_type,
                     inputs={k: [v.name for v in vs]
                             for k, vs in inputs.items()},
                     outputs=dict(zip(slots, [[o.name] for o in outs])),
                     attrs=attrs or {})
    return outs


def sequence_expand(x, y, ref_level=-1, out_len=None, name=None):
    """Repeat row i of x by a per-row count (reference sequence_expand,
    layers/sequence_lod.py:596 + sequence_ops/sequence_expand_op.h).

    Dense TPU encoding: ``y`` is the repeat-count int vector (N,) — the
    dense stand-in for the reference's y-LoD at ref_level — and ``out_len``
    is the STATIC row capacity of the output (>= the dynamic total; rows
    past the total come back zeroed). Returns (out, out_length) where
    out_length is the (1,) dynamic total, mirroring the repo-wide
    ragged->dense+lengths design.
    """
    if out_len is None:
        raise ValueError(
            "sequence_expand on TPU needs a static out_len capacity "
            "(XLA shapes are fixed at trace time); pass e.g. "
            "N * max_repeat")
    out, out_length = _seq_op(
        "sequence_expand", {"X": [x], "RepeatCounts": [y]}, n_out=2,
        dtypes=[x.dtype, "int32"],
        attrs={"out_len": int(out_len), "ref_level": ref_level}, name=name)
    return out, out_length


def sequence_expand_as(x, y, lengths=None, name=None):
    """Broadcast rows of x (N, D) over y's (N, T, ...) time dimension,
    zeroed past each length (reference sequence_expand_as_op)."""
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x.name], "Y": [y.name]}
    if lengths is not None:
        inputs["Length"] = [lengths.name]
    helper.append_op("sequence_expand_as", inputs=inputs,
                     outputs={"Out": [out.name]})
    return out


def sequence_concat(input, name=None):
    from .tensor import concat
    return concat(input, axis=1)


def sequence_first_step(input):
    from .nn import slice as slice_layer, squeeze
    s = slice_layer(input, axes=[1], starts=[0], ends=[1])
    return squeeze(s, axes=[1])


def sequence_last_step(input, lengths=None):
    from .nn import slice as slice_layer, squeeze, gather_nd
    if lengths is None:
        s = slice_layer(input, axes=[1], starts=[-1],
                        ends=[input.shape[1] + 1])
        return squeeze(s, axes=[1])
    # gather per-row step len_i - 1: take_along_axis via sequence_slice
    # (offset = len-1, slice length = 1)
    from .nn import elementwise_sub
    one = tensor_layers.fill_constant_batch_size_like(
        lengths, shape=[-1], dtype="int32", value=1)
    offset = elementwise_sub(lengths, one)
    out, _ = _seq_op("sequence_slice",
                     {"X": [input], "Offset": [offset],
                      "SliceLength": [one]}, n_out=2,
                     dtypes=[input.dtype, "int32"])
    # slice keeps T (left-aligned); the gathered step sits at t=0
    return squeeze(_slice_time(out, 0, 1), axes=[1])


def sequence_reverse(x, lengths=None, name=None):
    """Reverse each valid prefix (reference sequence_reverse_op); without
    lengths this is a plain time-axis reverse."""
    if lengths is None:
        from .tensor import reverse
        return reverse(x, axis=[1])
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sequence_reverse",
                     inputs={"X": [x.name], "Length": [lengths.name]},
                     outputs={"Y": [out.name]})
    return out


def sequence_pad(x, pad_value=0.0, maxlen=None, lengths=None, name=None):
    """Dense input is already rectangular; this masks everything past each
    row's length to pad_value (and re-caps T at maxlen when given),
    returning (out, lengths) like the reference."""
    helper = LayerHelper("sequence_pad_dense", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    lens_out = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [x.name]}
    if lengths is not None:
        inputs["Length"] = [lengths.name]
    helper.append_op("sequence_pad_dense", inputs=inputs,
                     outputs={"Out": [out.name], "Length": [lens_out.name]},
                     attrs={"pad_value": float(pad_value),
                            "padded_length": maxlen if maxlen else -1})
    lens_out.stop_gradient = True
    return out, lens_out


def sequence_unpad(x, length, name=None):
    """Zero the padded region (the dense analogue of stripping padding)."""
    out, _ = sequence_pad(x, pad_value=0.0, lengths=length, name=name)
    return out


def sequence_erase(x, tokens, lengths=None, pad_value=0, name=None):
    """Drop listed tokens and left-compact (reference sequence_erase_op).
    Returns (out, new_lengths)."""
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    new_len = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [x.name]}
    if lengths is not None:
        inputs["Length"] = [lengths.name]
    helper.append_op("sequence_erase", inputs=inputs,
                     outputs={"Out": [out.name], "OutLength": [new_len.name]},
                     attrs={"tokens": list(tokens), "pad_value": pad_value})
    out.stop_gradient = new_len.stop_gradient = True
    return out, new_len


def sequence_enumerate(input, win_size, pad_value=0, lengths=None, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    shape = None
    if input.shape is not None:
        shape = tuple(input.shape) + (win_size,)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    inputs = {"X": [input.name]}
    if lengths is not None:
        inputs["Length"] = [lengths.name]
    helper.append_op("sequence_enumerate", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"win_size": win_size, "pad_value": pad_value})
    out.stop_gradient = True
    return out


def sequence_slice(input, offset, length, name=None):
    """Per-row subsequence starting at offset[i] of length[i], left-aligned
    (reference sequence_slice_op). Returns (out, out_lengths)."""
    out, out_len = _seq_op("sequence_slice",
                           {"X": [input], "Offset": [offset],
                            "SliceLength": [length]}, n_out=2,
                           dtypes=[input.dtype, "int32"], name=name)
    out_len.stop_gradient = True
    return out, out_len


def sequence_reshape(input, new_dim, lengths=None):
    """Re-chunk token dim (reference sequence_reshape_op): total payload per
    row is constant, so T*D -> (T*D/new_dim, new_dim). Lengths scale by
    D/new_dim (caller guarantees divisibility, as the reference enforces).

    Returns the reshaped tensor alone (fluid-compatible) when lengths is
    None; with lengths it returns (out, new_lengths)."""
    from .nn import reshape, scale as scale_layer
    from .tensor import cast
    t, d = input.shape[-2], input.shape[-1]
    out = reshape(input, shape=[0, t * d // new_dim, new_dim])
    if lengths is None:
        return out
    scaled = scale_layer(cast(lengths, "float32"), scale=float(d) / new_dim)
    return out, cast(scaled, "int32")


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, lengths=None, name=None):
    """Context-window convolution over time (reference sequence_conv_op):
    im2col the +/- context window then one matmul — MXU-friendly.
    padding_start defaults to -(filter_size-1)/2 (centered window)."""
    from .nn import matmul
    if filter_stride != 1:
        raise ValueError("sequence_conv supports filter_stride=1 only "
                         "(as the reference op enforces)")
    helper = LayerHelper("sequence_conv", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = helper.input_dtype()
    d = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[filter_size * d, num_filters],
                                dtype=dtype)
    if padding_start is None:
        padding_start = -((filter_size - 1) // 2)
    # window stack: (N, T, filter_size*D) via shifted concat
    shifted = []
    from .tensor import concat
    from .nn import pad as _pad
    t = input.shape[1]
    seq_mask = None
    if lengths is not None:
        # zero the pad region first: shifted windows near the end of each
        # row's valid prefix would otherwise pull in whatever garbage sits
        # past its length (the output mask below can't undo that).
        seq_mask = sequence_mask(lengths, maxlen=t, dtype=dtype)
        input = elementwise_mul(input, unsqueeze(seq_mask, [2]))
    for k in range(filter_size):
        off = padding_start + k
        if off == 0:
            shifted.append(input)
        elif off < 0:
            padded = _pad(input, paddings=[0, 0, -off, 0, 0, 0])
            shifted.append(
                _slice_time(padded, 0, t))
        else:
            padded = _pad(input, paddings=[0, 0, 0, off, 0, 0])
            shifted.append(_slice_time(padded, off, off + t))
    windows = concat(shifted, axis=2)           # (N, T, K*D)
    out = matmul(windows, w)
    pre_act = helper.append_bias_op(out, dim_start=2)
    res = helper.append_activation(pre_act)
    if seq_mask is not None:
        res = elementwise_mul(res, unsqueeze(seq_mask, [2]))
    return res


def _slice_time(x, start, end):
    from .nn import slice as slice_layer
    return slice_layer(x, axes=[1], starts=[start], ends=[end])


def sequence_scatter(input, index, updates, lengths=None, name=None):
    """Scatter per-row updates into per-row positions (ref
    sequence_ops/sequence_scatter_op.h). Dense form: input (N, T),
    index (N, K) positions, updates (N, K) values added at those
    positions (duplicates accumulate, matching scatter-add); lengths
    (N,) masks each row's padded tail of (index, updates) pairs."""
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    if lengths is not None:
        ins["Length"] = [lengths]
    out, = _seq_op("sequence_scatter", ins, n_out=1, name=name)
    return out
