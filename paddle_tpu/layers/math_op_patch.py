"""fluid.layers.math_op_patch parity (ref layers/math_op_patch.py).
The reference monkey-patches Variable with arithmetic dunders at import
time; here they are defined directly on framework.program.Variable, so
monkey_patch_variable is a verified no-op."""
from ..framework.program import Variable

__all__ = ["monkey_patch_variable"]


def monkey_patch_variable():
    assert hasattr(Variable, "__add__") and hasattr(Variable, "__mul__")
