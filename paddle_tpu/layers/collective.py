"""Collective layers.

Reference parity: python/paddle/fluid/layers/collective.py (_c_allreduce,
_c_allgather, ...). On TPU these lower to XLA collectives over the mesh
(ops/collective_ops.py); axis_name selects the mesh axis (default "dp").
"""
from ..layer_helper import LayerHelper


def _collective(op_type, x, attrs=None, out_shape=None):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(
        x.dtype, out_shape if out_shape is not None else x.shape)
    helper.append_op(op_type, inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs=attrs or {})
    return out


def c_allreduce(x, op="sum", axis_name="dp", use_calc_stream=True):
    return _collective("c_allreduce_" + op, x, {"axis_name": axis_name})


def c_allreduce_sum(x, axis_name="dp"):
    return _collective("c_allreduce_sum", x, {"axis_name": axis_name})


def c_allreduce_sum_quant(x, axis_name="dp", block_size=256, bits=8):
    """Block-quantized allreduce (EQuARX): the wire carries int8 blocks
    + per-block fp32 scales instead of full-width values. Same identity-
    outside-shard_map contract as c_allreduce_sum."""
    return _collective("c_allreduce_sum_quant", x,
                       {"axis_name": axis_name,
                        "block_size": int(block_size), "bits": int(bits)})


def c_allgather(x, nranks=None, axis_name="dp"):
    shape = None
    if x.shape is not None and nranks:
        shape = (x.shape[0] * nranks,) + tuple(x.shape[1:])
    return _collective("c_allgather", x, {"axis_name": axis_name}, shape)


def c_reducescatter(x, nranks=None, axis_name="dp"):
    shape = None
    if x.shape is not None and nranks:
        shape = (x.shape[0] // nranks,) + tuple(x.shape[1:])
    return _collective("c_reducescatter", x, {"axis_name": axis_name}, shape)


def c_broadcast(x, root=0, axis_name="dp"):
    return _collective("c_broadcast", x, {"axis_name": axis_name,
                                          "root": root})


def ppermute(x, shift=1, axis_name="sp"):
    """Ring shift along a mesh axis (sequence-parallel building block)."""
    return _collective("ppermute", x, {"axis_name": axis_name,
                                       "shift": shift})


def barrier(x, axis_name="dp"):
    return _collective("barrier", x, {"axis_name": axis_name})
