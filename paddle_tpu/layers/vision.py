"""Layer tail: 3-D conv/pool family, spatial sampling, video ops, misc
tensor layers, CRF wrappers.

Reference parity: python/paddle/fluid/layers/nn.py — conv3d (:1410),
pool3d (:1888), adaptive_pool3d (:2249), conv3d_transpose (:3542),
affine_grid (:8314), grid_sampler (:11840), pixel_shuffle (:12711),
lrn (:5965), multiplex (:5177), crop (:8005), crop_tensor (:8111),
cos_sim (:735), bilinear_tensor_product (:12055), unfold (:13266),
unique (:12951), mean_iou (:7944), chunk_eval (:864), row_conv (:5137),
data_norm (:2776), temporal_shift (:12250), deformable_conv (:13046),
psroi_pool (:12587), prroi_pool (:12653), linear_chain_crf (:552),
crf_decoding (:672). Same signatures; kernels are the pure-JAX ops in
ops/vision_ops.py, ops/misc_ops.py, ops/crf_ops.py.
"""
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer, NormalInitializer


def _triple(v):
    return [v, v, v] if isinstance(v, int) else list(v)


def _conv3_out(i, k, p, s, d=1, ceil=False):
    if i in (None, -1):
        return -1
    num = i + 2 * p - (d * (k - 1) + 1)
    out = (-(-num // s) if ceil else num // s) + 1
    if ceil and (out - 1) * s >= i + p:
        out -= 1  # last window must start inside input+left-pad (ref/torch)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    groups = groups or 1
    num_channels = input.shape[1]
    filter_size = _triple(filter_size)
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    fan = filter_size[0] * filter_size[1] * filter_size[2] * num_channels
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, (2.0 / fan) ** 0.5))
    out_sp = [_conv3_out(input.shape[2 + i], filter_size[i], padding[i],
                         stride[i], dilation[i]) for i in range(3)]
    pre_bias = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], num_filters) + tuple(out_sp))
    helper.append_op(
        "conv3d", inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d_transpose", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = helper.input_dtype()
    groups = groups or 1
    num_channels = input.shape[1]
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    if output_size is not None:
        output_size = _triple(output_size)
    if filter_size is None:
        # Reference conv_transpose derives the kernel from output_size:
        # out = (in-1)*s - 2p + d*(k-1) + 1  =>  k.
        if output_size is None:
            raise ValueError(
                "conv3d_transpose needs filter_size or output_size")
        if any(input.shape[2 + i] in (None, -1) for i in range(3)):
            raise ValueError(
                "conv3d_transpose cannot derive filter_size from "
                "output_size when input spatial dims are dynamic — pass "
                "filter_size explicitly")
        filter_size = [
            (output_size[i] - (input.shape[2 + i] - 1) * stride[i] +
             2 * padding[i] - 1) // dilation[i] + 1 for i in range(3)]
        if any(k <= 0 for k in filter_size):
            raise ValueError(
                "conv3d_transpose: output_size %s too small for "
                "input/stride/padding (derived filter_size %s)"
                % (list(output_size), filter_size))
    else:
        filter_size = _triple(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    out_sp = []
    for i in range(3):
        s_in = input.shape[2 + i]
        derived = (-1 if s_in in (None, -1) else
                   (s_in - 1) * stride[i] - 2 * padding[i] +
                   dilation[i] * (filter_size[i] - 1) + 1)
        if output_size is not None:
            # Any size in [derived, derived + stride - 1] maps back to the
            # same input extent (same check as ref conv_transpose_op.cc).
            if derived != -1 and not (
                    derived <= output_size[i] < derived + stride[i]):
                raise ValueError(
                    "conv3d_transpose output_size[%d]=%d incompatible with "
                    "input/stride/padding (valid range [%d, %d))"
                    % (i, output_size[i], derived, derived + stride[i]))
            out_sp.append(output_size[i])
        else:
            out_sp.append(derived)
    pre_bias = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], num_filters) + tuple(out_sp))
    attrs = {"strides": stride, "paddings": padding, "dilations": dilation,
             "groups": groups}
    if output_size is not None:
        attrs["output_size"] = list(output_size)
    helper.append_op(
        "conv3d_transpose",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs=attrs)
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCDHW"):
    helper = LayerHelper("pool3d", name=name)
    pool_size = _triple(pool_size)
    pool_stride = _triple(pool_stride)
    pool_padding = _triple(pool_padding)
    if global_pooling:
        shape = (input.shape[0], input.shape[1], 1, 1, 1)
    else:
        sp = [_conv3_out(input.shape[2 + i], pool_size[i], pool_padding[i],
                         pool_stride[i], ceil=ceil_mode) for i in range(3)]
        shape = (input.shape[0], input.shape[1]) + tuple(sp)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op(
        "pool3d", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "strides": pool_stride, "paddings": pool_padding,
               "global_pooling": global_pooling, "exclusive": exclusive,
               "ceil_mode": ceil_mode})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if require_index:
        raise NotImplementedError("require_index is not supported on TPU "
                                  "(no stable argmax indices under XLA "
                                  "reduce-window)")
    helper = LayerHelper("adaptive_pool3d", name=name)
    pool_size = _triple(pool_size)
    shape = (input.shape[0], input.shape[1]) + tuple(pool_size)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op(
        "pool3d", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "adaptive": True})
    return out


# ---------------------------------------------------------------------------
# spatial sampling
# ---------------------------------------------------------------------------

def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", name=name)
    if not isinstance(out_shape, (list, tuple)):
        raise ValueError(
            "affine_grid on TPU needs out_shape as a static list/tuple "
            "[N, C, H, W] — XLA shapes are fixed at trace time, so a "
            "Variable out_shape (reference affine_grid_op OutputShape "
            "input) cannot be read here")
    out = helper.create_variable_for_type_inference(
        theta.dtype, (theta.shape[0], out_shape[2], out_shape[3], 2))
    helper.append_op("affine_grid", inputs={"Theta": [theta.name]},
                     outputs={"Output": [out.name]},
                     attrs={"output_shape": [int(s) for s in out_shape]})
    return out


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    shape = (x.shape[0], x.shape[1], grid.shape[1], grid.shape[2])
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("grid_sampler",
                     inputs={"X": [x.name], "Grid": [grid.name]},
                     outputs={"Output": [out.name]})
    return out


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle")
    r = int(upscale_factor)
    n, c, h, w = x.shape
    out = helper.create_variable_for_type_inference(
        x.dtype, (n, c // (r * r), h * r, w * r))
    helper.append_op("pixel_shuffle", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"upscale_factor": r})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    mid = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("lrn", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "MidOut": [mid.name]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", name=name)
    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) \
        else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    pd = [paddings] * 2 if isinstance(paddings, int) else list(paddings)
    dl = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("unfold", inputs={"X": [x.name]},
                     outputs={"Y": [out.name]},
                     attrs={"kernel_sizes": ks, "strides": st,
                            "paddings": pd, "dilations": dl})
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    helper = LayerHelper("temporal_shift", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("temporal_shift", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"seg_num": int(seg_num),
                            "shift_ratio": float(shift_ratio)})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", input=input, param_attr=param_attr,
                         act=act)
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype, input.shape)
    helper.append_op("row_conv",
                     inputs={"X": [input.name], "Filter": [w.name]},
                     outputs={"Out": [out.name]})
    return helper.append_activation(out)


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None, modulated=True,
                    name=None):
    helper = LayerHelper("deformable_conv", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dtype = helper.input_dtype()
    groups = groups or 1
    deformable_groups = deformable_groups or 1
    num_channels = input.shape[1]
    fs = [filter_size] * 2 if isinstance(filter_size, int) \
        else list(filter_size)
    stride = [stride] * 2 if isinstance(stride, int) else list(stride)
    padding = [padding] * 2 if isinstance(padding, int) else list(padding)
    dilation = [dilation] * 2 if isinstance(dilation, int) \
        else list(dilation)
    filter_shape = [num_filters, num_channels // groups] + fs
    fan = fs[0] * fs[1] * num_channels
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, (2.0 / fan) ** 0.5))
    inputs = {"Input": [input.name], "Offset": [offset.name],
              "Filter": [w.name]}
    if modulated:
        if mask is None:
            raise ValueError("modulated deformable_conv (v2) requires mask")
        inputs["Mask"] = [mask.name]
    oh = _conv3_out(input.shape[2], fs[0], padding[0], stride[0], dilation[0])
    ow = _conv3_out(input.shape[3], fs[1], padding[1], stride[1], dilation[1])
    pre_bias = helper.create_variable_for_type_inference(
        dtype, (input.shape[0], num_filters, oh, ow))
    helper.append_op(
        "deformable_conv", inputs=inputs,
        outputs={"Output": [pre_bias.name]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups, "deformable_groups": deformable_groups})
    return helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "psroi_pool", inputs={"X": [input.name], "ROIs": [rois.name]},
        outputs={"Out": [out.name]},
        attrs={"output_channels": int(output_channels),
               "spatial_scale": float(spatial_scale),
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width)})
    return out


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    helper = LayerHelper("prroi_pool", name=name)
    inputs = {"X": [input.name], "ROIs": [rois.name]}
    if batch_roi_nums is not None:
        inputs["BatchRoINums"] = [batch_roi_nums.name]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "prroi_pool", inputs=inputs, outputs={"Out": [out.name]},
        attrs={"spatial_scale": float(spatial_scale),
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width)})
    return out


# ---------------------------------------------------------------------------
# misc tensor layers
# ---------------------------------------------------------------------------

def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(
        inputs[0].dtype, inputs[0].shape)
    helper.append_op("multiplex",
                     inputs={"X": [v.name for v in inputs],
                             "Ids": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", name=name)
    attrs = {}
    inputs = {"X": [x.name]}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = [int(s) for s in shape]
        out_shape = tuple(int(s) for s in shape)
    else:                                   # Variable: take its static shape
        inputs["Y"] = [shape.name]
        out_shape = tuple(shape.shape)
    if offsets is not None:
        attrs["offsets"] = [int(o) for o in offsets]
    out = helper.create_variable_for_type_inference(x.dtype, out_shape)
    helper.append_op("crop", inputs=inputs, outputs={"Out": [out.name]},
                     attrs=attrs)
    return out


def crop_tensor(x, shape=None, offsets=None, name=None):
    return crop(x, shape=shape, offsets=offsets, name=name)


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(X.dtype, (X.shape[0], 1))
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("cos_sim", inputs={"X": [X.name], "Y": [Y.name]},
                     outputs={"Out": [out.name], "XNorm": [xn.name],
                              "YNorm": [yn.name]})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = x.dtype
    w = helper.create_parameter(
        helper.param_attr, shape=[size, x.shape[1], y.shape[1]], dtype=dtype)
    inputs = {"X": [x.name], "Y": [y.name], "Weight": [w.name]}
    bias = helper.create_parameter(helper.bias_attr, shape=[1, size],
                                   dtype=dtype, is_bias=True)
    if bias is not None:
        inputs["Bias"] = [bias.name]
    out = helper.create_variable_for_type_inference(dtype, (x.shape[0], size))
    helper.append_op("bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out.name]})
    return helper.append_activation(out)


def unique(x, dtype="int32"):
    """TPU deviation (static shapes): Out is sorted and padded to len(x);
    the number of valid leading entries is in the 3rd return value."""
    helper = LayerHelper("unique")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    index = helper.create_variable_for_type_inference(dtype, x.shape)
    count = helper.create_variable_for_type_inference("int32", ())
    helper.append_op("unique", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Index": [index.name],
                              "Count": [count.name]})
    for v in (out, index, count):
        v.stop_gradient = True
    return out, index, count


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    index = helper.create_variable_for_type_inference(dtype, x.shape)
    counts = helper.create_variable_for_type_inference(dtype, x.shape)
    count = helper.create_variable_for_type_inference("int32", ())
    helper.append_op("unique_with_counts", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Index": [index.name],
                              "Counts": [counts.name],
                              "Count": [count.name]})
    for v in (out, index, counts, count):
        v.stop_gradient = True
    return out, index, counts


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32", ())
    wrong = helper.create_variable_for_type_inference("int32", (num_classes,))
    correct = helper.create_variable_for_type_inference(
        "int32", (num_classes,))
    helper.append_op("mean_iou",
                     inputs={"Predictions": [input.name],
                             "Labels": [label.name]},
                     outputs={"OutMeanIou": [miou.name],
                              "OutWrong": [wrong.name],
                              "OutCorrect": [correct.name]},
                     attrs={"num_classes": int(num_classes)})
    for v in (miou, wrong, correct):
        v.stop_gradient = True
    return miou, wrong, correct


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    helper = LayerHelper("chunk_eval")
    names = ["Precision", "Recall", "F1-Score", "NumInferChunks",
             "NumLabelChunks", "NumCorrectChunks"]
    dts = ["float32"] * 3 + ["int32"] * 3
    outs = [helper.create_variable_for_type_inference(dt, (1,))
            for dt in dts]
    inputs = {"Inference": [input.name], "Label": [label.name]}
    if seq_length is not None:
        inputs["SeqLength"] = [seq_length.name]
    helper.append_op(
        "chunk_eval", inputs=inputs,
        outputs={s: [v.name] for s, v in zip(names, outs)},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": int(num_chunk_types),
               "excluded_chunk_types": list(excluded_chunk_types or [])})
    for v in outs:
        v.stop_gradient = True
    return tuple(outs)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    helper = LayerHelper("data_norm", param_attr=param_attr, act=act,
                         name=name)
    c = input.shape[1]
    from ..framework import unique_name as _un
    bsize = helper.create_or_get_global_variable(
        name=_un.generate(helper.name + ".batch_size"), dtype="float32",
        shape=(c,), persistable=True)
    helper.set_variable_initializer(bsize, ConstantInitializer(1e4))
    bsum = helper.create_or_get_global_variable(
        name=_un.generate(helper.name + ".batch_sum"), dtype="float32",
        shape=(c,), persistable=True)
    helper.set_variable_initializer(bsum, ConstantInitializer(0.0))
    bsq = helper.create_or_get_global_variable(
        name=_un.generate(helper.name + ".batch_square_sum"),
        dtype="float32", shape=(c,), persistable=True)
    helper.set_variable_initializer(bsq, ConstantInitializer(1e4))
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    means = helper.create_variable_for_type_inference("float32", (c,))
    scales = helper.create_variable_for_type_inference("float32", (c,))
    helper.append_op(
        "data_norm",
        inputs={"X": [input.name], "BatchSize": [bsize.name],
                "BatchSum": [bsum.name], "BatchSquareSum": [bsq.name]},
        outputs={"Y": [out.name], "Means": [means.name],
                 "Scales": [scales.name], "BatchSizeOut": [bsize.name],
                 "BatchSumOut": [bsum.name], "BatchSquareSumOut": [bsq.name]},
        attrs={"epsilon": epsilon})
    return helper.append_activation(out)


# ---------------------------------------------------------------------------
# CRF wrappers (kernels: ops/crf_ops.py)
# ---------------------------------------------------------------------------

def linear_chain_crf(input, label, param_attr=None, length=None):
    """Dense-batch CRF log-likelihood. input (N,T,C) emissions, label
    (N,T) or (N,T,1); transition parameter shape (C+2, C) — rows 0/1 are
    start/stop scores, as in the reference."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        helper.param_attr, shape=[size + 2, size], dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(
        "float32", (input.shape[0], 1))
    alpha = helper.create_variable_for_type_inference("float32")
    em_exps = helper.create_variable_for_type_inference("float32")
    tr_exps = helper.create_variable_for_type_inference("float32")
    inputs = {"Emission": [input.name], "Transition": [transition.name],
              "Label": [label.name]}
    if length is not None:
        inputs["Length"] = [length.name]
    helper.append_op(
        "linear_chain_crf", inputs=inputs,
        outputs={"LogLikelihood": [ll.name], "Alpha": [alpha.name],
                 "EmissionExps": [em_exps.name],
                 "TransitionExps": [tr_exps.name]})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode using the transition parameter learned by
    linear_chain_crf (pass the same param_attr/name)."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        helper.param_attr, shape=[size + 2, size], dtype=input.dtype)
    path = helper.create_variable_for_type_inference(
        "int64", tuple(input.shape[:-1]) + (1,))
    inputs = {"Emission": [input.name], "Transition": [transition.name]}
    if label is not None:
        inputs["Label"] = [label.name]
    if length is not None:
        inputs["Length"] = [length.name]
    helper.append_op("crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [path.name]})
    path.stop_gradient = True
    return path
