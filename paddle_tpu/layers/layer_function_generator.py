"""Layer-function codegen utilities
(ref python/paddle/fluid/layers/layer_function_generator.py).

The reference generates Python layer functions from C++ OpProto
metadata; here the registry (ops/registry.py) plays the proto role:
``generate_layer_fn(op_type)`` returns a layer that appends the op with
single X->Out slots (the shape the generated fluid layers take), and
``generate_activation_fn`` is its activation specialization.  The doc
decorators are kept as identity-with-annotation shims so fluid code
importing them keeps working.
"""
import functools
import warnings

from ..layer_helper import LayerHelper

__all__ = ["generate_layer_fn", "generate_activation_fn", "deprecated",
           "autodoc", "templatedoc"]


def generate_layer_fn(op_type):
    """Build a layers-style function for a registered elementwise-shaped
    op (ref :133): fn(x, name=None, **attrs) -> out var."""
    from ..ops.registry import get_op
    get_op(op_type)  # fail fast on unknown ops

    def layer_fn(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
        helper.append_op(op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out

    layer_fn.__name__ = op_type
    layer_fn.__doc__ = "Auto-generated layer for the %r op." % op_type
    return layer_fn


def generate_activation_fn(op_type):
    """Activation specialization of generate_layer_fn (ref :242)."""
    return generate_layer_fn(op_type)


def deprecated(func_or_class):
    """Mark an API deprecated (ref :299): warns once per call site."""

    @functools.wraps(func_or_class)
    def wrapper(*args, **kwargs):
        warnings.warn(
            "API %r is deprecated" % func_or_class.__name__,
            DeprecationWarning, stacklevel=2)
        return func_or_class(*args, **kwargs)

    return wrapper


def autodoc(comment=""):
    """Docstring annotator (ref :321)."""

    def decorator(func):
        func.__doc__ = comment + (func.__doc__ or "")
        return func

    return decorator


def templatedoc(op_type=None):
    """Template-docstring annotator (ref :330) — the proto comments the
    reference substitutes do not exist here, so placeholders are left
    in place."""

    def decorator(func):
        return func

    return decorator
