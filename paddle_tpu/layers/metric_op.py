"""Metric layers (accuracy, auc).

Reference parity: python/paddle/fluid/layers/metric_op.py.
"""
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer
from .nn import topk


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    values, indices = topk(input, k=k)
    acc = helper.create_variable_for_type_inference("float32", (1,))
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32", (1,))
    if total is None:
        total = helper.create_variable_for_type_inference("int32", (1,))
    helper.append_op(
        "accuracy",
        inputs={"Out": [values.name], "Indices": [indices.name],
                "Label": [label.name]},
        outputs={"Accuracy": [acc.name], "Correct": [correct.name],
                 "Total": [total.name]})
    acc.stop_gradient = True
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    helper = LayerHelper("auc")
    stat_pos = helper.create_or_get_global_variable(
        name=helper.name + "_stat_pos", dtype="int64",
        shape=(num_thresholds + 1,), persistable=True)
    helper.set_variable_initializer(stat_pos, ConstantInitializer(0.0))
    stat_neg = helper.create_or_get_global_variable(
        name=helper.name + "_stat_neg", dtype="int64",
        shape=(num_thresholds + 1,), persistable=True)
    helper.set_variable_initializer(stat_neg, ConstantInitializer(0.0))
    auc_out = helper.create_variable_for_type_inference("float32", (1,))
    helper.append_op(
        "auc",
        inputs={"Predict": [input.name], "Label": [label.name],
                "StatPos": [stat_pos.name], "StatNeg": [stat_neg.name]},
        outputs={"AUC": [auc_out.name], "StatPosOut": [stat_pos.name],
                 "StatNegOut": [stat_neg.name]},
        attrs={"num_thresholds": num_thresholds, "curve": curve})
    auc_out.stop_gradient = True
    return auc_out, [stat_pos, stat_neg]
