"""Tensor-creation / manipulation layers.

Reference parity: python/paddle/fluid/layers/tensor.py.
"""
import numpy as np

from ..layer_helper import LayerHelper
from ..framework.program import Variable
from ..framework import unique_name


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr
    helper = LayerHelper("create_parameter", name=name)
    attr = ParamAttr._to_attr(attr)
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..initializer import ConstantInitializer
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable,
        name=name or unique_name.generate("global_var"))
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    from ..framework.dtypes import normalize_dtype
    dtype = normalize_dtype(dtype)
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype, x.shape)
    helper.append_op("cast", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    shape = None
    if all(i.shape is not None for i in input):
        ax = axis % len(input[0].shape)
        dims = [i.shape[ax] for i in input]
        shape = list(input[0].shape)
        shape[ax] = -1 if any(d == -1 for d in dims) else sum(dims)
    out = helper.create_variable_for_type_inference(input[0].dtype, shape)
    helper.append_op("concat", inputs={"X": [i.name for i in input]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype,
                                                        input[0].shape)
    helper.append_op("sum", inputs={"X": [i.name for i in input]},
                     outputs={"Out": [out.name]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype,
                                                               input.shape)
        helper.append_op("assign", inputs={"X": [input.name]},
                         outputs={"Out": [output.name]})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(arr.dtype), arr.shape)
        helper.append_op("assign_value", outputs={"Out": [output.name]},
                         attrs={"shape": list(arr.shape),
                                "dtype": output.dtype,
                                "values": arr.reshape(-1).tolist()})
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("fill_constant", outputs={"Out": [out.name]},
                     attrs={"shape": [int(s) for s in shape],
                            "dtype": dtype, "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    """Static-shape TPU variant: batch dim is taken from input's shape at
    trace time via fill_any_like when ranks allow, else from declared shape."""
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op(
        "fill_constant_batch_size_like",
        inputs={"Input": [input.name]}, outputs={"Out": [out.name]},
        attrs={"shape": [int(s) for s in shape], "dtype": dtype,
               "value": float(value), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx})
    return out


def _argminmax_shape(x, axis):
    if x.shape is None:
        return None
    nd = len(x.shape)
    return tuple(s for i, s in enumerate(x.shape) if i != axis % nd)


def argmin(x, axis=0):
    helper = LayerHelper("argmin")
    out = helper.create_variable_for_type_inference(
        "int64", _argminmax_shape(x, axis))
    helper.append_op("arg_min", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    out.stop_gradient = True
    return out


def argmax(x, axis=0):
    helper = LayerHelper("argmax")
    out = helper.create_variable_for_type_inference(
        "int64", _argminmax_shape(x, axis))
    helper.append_op("arg_max", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    out.stop_gradient = True
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    ids = helper.create_variable_for_type_inference("int64", input.shape)
    helper.append_op("argsort", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "Indices": [ids.name]},
                     attrs={"axis": axis, "descending": descending})
    ids.stop_gradient = True
    return out, ids


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("fill_zeros_like", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("fill_any_like", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"value": 1.0})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    axis = [axis] if isinstance(axis, int) else list(axis)
    helper.append_op("flip", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    if not isinstance(start, Variable):
        start = fill_constant([1], dtype, start)
    if not isinstance(end, Variable):
        end = fill_constant([1], dtype, end)
    if not isinstance(step, Variable):
        step = fill_constant([1], dtype, step)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("range", inputs={"Start": [start.name],
                                      "End": [end.name],
                                      "Step": [step.name]},
                     outputs={"Out": [out.name]})
    return out


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    if not isinstance(start, Variable):
        start = fill_constant([1], dtype, start)
    if not isinstance(stop, Variable):
        stop = fill_constant([1], dtype, stop)
    if not isinstance(num, Variable):
        num = fill_constant([1], "int32", num)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("linspace", inputs={"Start": [start.name],
                                         "Stop": [stop.name],
                                         "Num": [num.name]},
                     outputs={"Out": [out.name]})
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye")
    num_columns = num_columns or num_rows
    out = helper.create_variable_for_type_inference(
        dtype, (num_rows, num_columns))
    helper.append_op("eye", outputs={"Out": [out.name]},
                     attrs={"num_rows": num_rows, "num_columns": num_columns,
                            "dtype": dtype})
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    helper.append_op("diag", inputs={"Diagonal": [diagonal.name]},
                     outputs={"Out": [out.name]})
    return out


def has_inf(x):
    helper = LayerHelper("isinf")
    out = helper.create_variable_for_type_inference("bool", (1,))
    helper.append_op("isinf", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def has_nan(x):
    helper = LayerHelper("isnan")
    out = helper.create_variable_for_type_inference("bool", (1,))
    helper.append_op("isnan", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference("bool", (1,))
    helper.append_op("isfinite", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """Stack/concat a (build-time) TensorArray into one tensor + the
    per-entry sizes (ref tensor.py tensor_array_to_tensor)."""
    import numpy as np
    from .nn import stack
    entries = [v for v in input if v is not None]
    if not entries:
        raise ValueError("tensor_array_to_tensor: empty array")
    if use_stack:
        out = stack(entries, axis=axis)
        sizes = [1] * len(entries)
    else:
        out = concat(entries, axis=axis)
        sizes = [int(v.shape[axis]) for v in entries]
    return out, assign(np.asarray(sizes, np.int32))
