"""paddle_tpu.layers — mirrors fluid.layers namespace."""
from .tensor import *        # noqa: F401,F403
from .ops import *           # noqa: F401,F403
from .nn import *            # noqa: F401,F403
from .loss import *          # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .metric_op import accuracy, auc  # noqa: F401
from .io import data         # noqa: F401
from . import learning_rate_scheduler  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup)
from .sequence_lod import *  # noqa: F401,F403
from .rnn import *           # noqa: F401,F403
from .attention import *     # noqa: F401,F403
from .collective import *    # noqa: F401,F403
from .distributions import Normal, Uniform, Categorical  # noqa: F401
