"""paddle_tpu.layers — mirrors fluid.layers namespace."""
from .tensor import *        # noqa: F401,F403
from .ops import *           # noqa: F401,F403
from .nn import *            # noqa: F401,F403
from .loss import *          # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .metric_op import accuracy, auc  # noqa: F401
from .io import (data, py_reader, read_file, double_buffer,  # noqa: F401
                 EOFException, create_py_reader_by_data, load)
from . import learning_rate_scheduler  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup)
from .sequence_lod import *  # noqa: F401,F403
from .vision import *        # noqa: F401,F403
from .extras import *        # noqa: F401,F403
from .rnn import *           # noqa: F401,F403
from .attention import *     # noqa: F401,F403
from .collective import *    # noqa: F401,F403
from .distributions import (Normal, Uniform, Categorical,  # noqa: F401
                            MultivariateNormalDiag)
from . import detection  # noqa: F401
from .detection import (  # noqa: F401
    prior_box, density_prior_box, multi_box_head, anchor_generator,
    bipartite_match, target_assign, detection_output, ssd_loss,
    sigmoid_focal_loss, iou_similarity, box_coder, polygon_box_transform,
    yolov3_loss, yolo_box, box_clip, multiclass_nms,
    distribute_fpn_proposals, collect_fpn_proposals, box_decoder_and_assign,
    generate_proposals, roi_align, roi_pool, rpn_target_assign,
    retinanet_target_assign, generate_proposal_labels,
    locality_aware_nms, retinanet_detection_output,
    roi_perspective_transform, generate_mask_labels)
# NOTE: binding the `rnn` FUNCTION here shadows the layers.rnn submodule
# attribute — fluid 1.6 has the same shadowing (layers.rnn is the scan
# entry point; reach the legacy module via `from paddle_tpu.layers import
# rnn as rnn_mod` / importlib if needed)
from .rnn_api import (RNNCell, GRUCell, LSTMCell, rnn, lstm,  # noqa: F401
                      dynamic_lstmp, Decoder, BeamSearchDecoder,
                      dynamic_decode, beam_search, beam_search_decode)
from . import rnn_api  # noqa: F401
from .layer_function_generator import (generate_layer_fn,  # noqa: F401
    generate_activation_fn, deprecated, autodoc, templatedoc)
from . import layer_function_generator  # noqa: F401
