"""fluid.layers.utils parity (ref python/paddle/fluid/layers/utils.py):
nest utilities shared by the RNN/decoder APIs, plus convert_to_list."""
import collections

__all__ = ["convert_to_list", "is_sequence", "flatten",
           "pack_sequence_as", "map_structure", "assert_same_structure"]


def convert_to_list(value, n, name, dtype=int):
    if isinstance(value, dtype):
        return [value] * n
    try:
        value_list = list(value)
    except TypeError:
        raise ValueError("The %s's type must be %s or list of %s" %
                         (name, dtype, dtype))
    if len(value_list) != n:
        raise ValueError("The %s's length must be %d" % (name, n))
    for v in value_list:
        if not isinstance(v, dtype):
            raise ValueError("The %s's type must be a list of %s" %
                             (name, dtype))
    return value_list


def is_sequence(seq):
    return isinstance(seq, collections.abc.Sequence) and \
        not isinstance(seq, str) or isinstance(seq, dict)


def _yield_flat(nest):
    if isinstance(nest, dict):
        for k in sorted(nest):
            for v in _yield_flat(nest[k]):
                yield v
    elif is_sequence(nest):
        for item in nest:
            for v in _yield_flat(item):
                yield v
    else:
        yield nest


def flatten(nest):
    return list(_yield_flat(nest)) if is_sequence(nest) else [nest]


def _pack(structure, flat, index):
    if isinstance(structure, dict):
        out = {}
        for k in sorted(structure):
            out[k], index = _pack(structure[k], flat, index)
        return type(structure)(out), index
    if is_sequence(structure):
        items = []
        for s in structure:
            item, index = _pack(s, flat, index)
            items.append(item)
        if isinstance(structure, tuple):
            if hasattr(structure, "_fields"):            # namedtuple
                return type(structure)(*items), index
            return tuple(items), index
        return type(structure)(items), index
    return flat[index], index + 1


def pack_sequence_as(structure, flat_sequence):
    if not is_sequence(structure):
        if len(flat_sequence) != 1:
            raise ValueError("structure is a scalar but there are %d "
                             "flat values" % len(flat_sequence))
        return flat_sequence[0]
    packed, used = _pack(structure, list(flat_sequence), 0)
    if used != len(flat_sequence):
        raise ValueError("could not pack %d values into the structure"
                         % len(flat_sequence))
    return packed


def map_structure(func, *structures):
    flats = [flatten(s) for s in structures]
    results = [func(*xs) for xs in zip(*flats)]
    return pack_sequence_as(structures[0], results)


def _same(a, b, check_types):
    if is_sequence(a) != is_sequence(b):
        raise ValueError("structures differ: %r vs %r" % (a, b))
    if not is_sequence(a):
        return
    if check_types and type(a) is not type(b) and not (
            hasattr(a, "_fields") and hasattr(b, "_fields") and
            type(a) is type(b)):
        raise ValueError("structure container types differ: %s vs %s"
                         % (type(a).__name__, type(b).__name__))
    if isinstance(a, dict) != isinstance(b, dict):
        raise ValueError("structures differ: %r vs %r" % (a, b))
    if isinstance(a, dict):
        if sorted(a) != sorted(b):
            raise ValueError("dict keys differ: %r vs %r" % (a, b))
        for k in a:
            _same(a[k], b[k], check_types)
        return
    if len(a) != len(b):
        raise ValueError("lengths differ: %d vs %d" % (len(a), len(b)))
    for x, y in zip(a, b):
        _same(x, y, check_types)


def assert_same_structure(nest1, nest2, check_types=True):
    _same(nest1, nest2, check_types)
