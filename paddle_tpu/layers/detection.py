"""Detection layer API — mirrors python/paddle/fluid/layers/detection.py.

Each function appends the corresponding registered op (ops/detection_ops.py)
to the current Program. Dynamic-length reference outputs (LoD tensors) map to
fixed-capacity tensors plus explicit counts/masks — the XLA-native encoding.
"""
from ..layer_helper import LayerHelper

__all__ = [
    'prior_box', 'density_prior_box', 'multi_box_head', 'anchor_generator',
    'bipartite_match', 'target_assign', 'detection_output', 'ssd_loss',
    'sigmoid_focal_loss', 'iou_similarity', 'box_coder',
    'polygon_box_transform', 'yolov3_loss', 'yolo_box', 'box_clip',
    'multiclass_nms', 'distribute_fpn_proposals', 'collect_fpn_proposals',
    'box_decoder_and_assign', 'generate_proposals', 'roi_align', 'roi_pool',
    'rpn_target_assign', 'retinanet_target_assign',
    'generate_proposal_labels', 'locality_aware_nms',
    'retinanet_detection_output', 'roi_perspective_transform',
    'generate_mask_labels',
]


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    shape = None
    if x.shape is not None and y.shape is not None:
        shape = (x.shape[0], y.shape[0])
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("iou_similarity", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    out.stop_gradient = True
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    if prior_box_var is not None and hasattr(prior_box_var, "name"):
        inputs["PriorBoxVar"] = [prior_box_var.name]
    helper.append_op("box_coder", inputs=inputs,
                     outputs={"OutputBox": [out.name]},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized, "axis": axis})
    out.stop_gradient = True
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    dtype = input.dtype
    box = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "prior_box", inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [box.name], "Variances": [var.name]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios),
               "variances": list(variance), "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset})
    box.stop_gradient = var.stop_gradient = True
    return box, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    dtype = input.dtype
    box = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "density_prior_box",
        inputs={"Input": [input.name], "Image": [image.name]},
        outputs={"Boxes": [box.name], "Variances": [var.name]},
        attrs={"densities": list(densities), "fixed_sizes": list(fixed_sizes),
               "fixed_ratios": list(fixed_ratios), "variances": list(variance),
               "clip": clip, "step_w": steps[0], "step_h": steps[1],
               "offset": offset, "flatten_to_2d": flatten_to_2d})
    box.stop_gradient = var.stop_gradient = True
    return box, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    dtype = input.dtype
    anchor = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "anchor_generator", inputs={"Input": [input.name]},
        outputs={"Anchors": [anchor.name], "Variances": [var.name]},
        attrs={"anchor_sizes": list(anchor_sizes or [64., 128., 256., 512.]),
               "aspect_ratios": list(aspect_ratios or [0.5, 1.0, 2.0]),
               "variances": list(variance),
               "stride": list(stride or [16.0, 16.0]), "offset": offset})
    anchor.stop_gradient = var.stop_gradient = True
    return anchor, var


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", name=name)
    match_indices = helper.create_variable_for_type_inference("int32")
    match_distance = helper.create_variable_for_type_inference(
        dist_matrix.dtype)
    helper.append_op(
        "bipartite_match", inputs={"DistMat": [dist_matrix.name]},
        outputs={"ColToRowMatchIndices": [match_indices.name],
                 "ColToRowMatchDist": [match_distance.name]},
        attrs={"match_type": "bipartite" if match_type is None
               else match_type,
               "dist_threshold": 0.5 if dist_threshold is None
               else dist_threshold})
    match_indices.stop_gradient = match_distance.stop_gradient = True
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    inputs = {"X": [input.name], "MatchIndices": [matched_indices.name]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices.name]
    helper.append_op("target_assign", inputs=inputs,
                     outputs={"Out": [out.name],
                              "OutWeight": [out_weight.name]},
                     attrs={"mismatch_value": 0 if mismatch_value is None
                            else mismatch_value})
    out.stop_gradient = out_weight.stop_gradient = True
    return out, out_weight


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("box_clip", inputs={"Input": [input.name],
                                         "ImInfo": [im_info.name]},
                     outputs={"Output": [out.name]})
    return out


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("polygon_box_transform", inputs={"Input": [input.name]},
                     outputs={"Output": [out.name]})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("yolo_box",
                     inputs={"X": [x.name], "ImgSize": [img_size.name]},
                     outputs={"Boxes": [boxes.name], "Scores": [scores.name]},
                     attrs={"anchors": list(anchors), "class_num": class_num,
                            "conf_thresh": conf_thresh,
                            "downsample_ratio": downsample_ratio})
    boxes.stop_gradient = scores.stop_gradient = True
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    objness = helper.create_variable_for_type_inference(x.dtype)
    match = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [x.name], "GTBox": [gt_box.name],
              "GTLabel": [gt_label.name]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score.name]
    helper.append_op(
        "yolov3_loss", inputs=inputs,
        outputs={"Loss": [loss.name], "ObjectnessMask": [objness.name],
                 "GTMatchMask": [match.name]},
        attrs={"anchors": list(anchors), "anchor_mask": list(anchor_mask),
               "class_num": class_num, "ignore_thresh": ignore_thresh,
               "downsample_ratio": downsample_ratio,
               "use_label_smooth": use_label_smooth})
    objness.stop_gradient = match.stop_gradient = True
    return loss


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    helper = LayerHelper("sigmoid_focal_loss")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sigmoid_focal_loss",
                     inputs={"X": [x.name], "Label": [label.name],
                             "FgNum": [fg_num.name]},
                     outputs={"Out": [out.name]},
                     attrs={"gamma": gamma, "alpha": alpha})
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             name=None):
    """Dense-gt SSD loss: gt_box (N, G, 4) zero-padded, gt_label (N, G)."""
    helper = LayerHelper("ssd_loss", name=name)
    loss = helper.create_variable_for_type_inference(location.dtype)
    inputs = {"Location": [location.name], "Confidence": [confidence.name],
              "GtBox": [gt_box.name], "GtLabel": [gt_label.name],
              "PriorBox": [prior_box.name]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    helper.append_op(
        "ssd_loss", inputs=inputs, outputs={"Loss": [loss.name]},
        attrs={"background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
               "loc_loss_weight": loc_loss_weight,
               "conf_loss_weight": conf_loss_weight,
               "match_type": match_type, "mining_type": mining_type,
               "normalize": normalize, "sample_size": sample_size or 0})
    return loss


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, return_index=False, name=None):
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    index = helper.create_variable_for_type_inference("int32")
    nums = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "multiclass_nms",
        inputs={"BBoxes": [bboxes.name], "Scores": [scores.name]},
        outputs={"Out": [out.name], "Index": [index.name],
                 "NmsRoisNum": [nums.name]},
        attrs={"score_threshold": score_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    out.stop_gradient = index.stop_gradient = nums.stop_gradient = True
    if return_index:
        return out, index
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """SSD inference head (reference layers/detection.py detection_output):
    decode loc deltas against priors then multiclass NMS. `scores` are
    post-softmax (N, P, C)."""
    from . import nn as _nn
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = _nn.transpose(scores, perm=[0, 2, 1])     # (N, C, P)
    return multiclass_nms(decoded, scores_t, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold, nms_eta=nms_eta,
                          background_label=background_label)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multibox head (reference layers/detection.py multi_box_head):
    per feature map a 3x3 conv for loc (+4/prior) and conf (+C/prior),
    priors from prior_box; outputs concatenated over maps."""
    from . import nn as _nn
    from . import tensor as _tensor
    n_layer = len(inputs)
    if min_sizes is None:
        # reference ratio interpolation
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_layer - 2))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes, vars_ = [], [], [], []
    for i, inp in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i] if max_sizes else None
        if not isinstance(min_size, (list, tuple)):
            min_size = [min_size]
        ar = aspect_ratios[i]
        if not isinstance(ar, (list, tuple)):
            ar = [ar]
        st = steps[i] if steps else (
            [step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0])
        if not isinstance(st, (list, tuple)):
            st = [st, st]
        box, var = prior_box(inp, image, min_size,
                             [max_size] if max_size else None, ar, variance,
                             flip, clip, st, offset)
        # same flip/dedup expansion as the prior_box kernel so the conv
        # channel count matches the kernel's prior count
        ars = [1.0]
        for a in ar:
            if not any(abs(a - x) < 1e-6 for x in ars):
                ars.append(a)
                if flip:
                    ars.append(1.0 / a)
        num_priors = len(min_size) * len(ars) + \
            (len(min_size) if max_size else 0)
        loc = _nn.conv2d(inp, num_priors * 4, kernel_size, padding=pad,
                         stride=stride)
        loc = _nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = _nn.reshape(loc, shape=[0, -1, 4])
        conf = _nn.conv2d(inp, num_priors * num_classes, kernel_size,
                          padding=pad, stride=stride)
        conf = _nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = _nn.reshape(conf, shape=[0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes.append(_nn.reshape(box, shape=[-1, 4]))
        vars_.append(_nn.reshape(var, shape=[-1, 4]))

    mbox_locs = _tensor.concat(locs, axis=1)
    mbox_confs = _tensor.concat(confs, axis=1)
    box = _tensor.concat(boxes, axis=0)
    var = _tensor.concat(vars_, axis=0)
    return mbox_locs, mbox_confs, box, var


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    helper = LayerHelper("box_decoder_and_assign", name=name)
    decoded = helper.create_variable_for_type_inference(prior_box.dtype)
    assigned = helper.create_variable_for_type_inference(prior_box.dtype)
    helper.append_op(
        "box_decoder_and_assign",
        inputs={"PriorBox": [prior_box.name],
                "PriorBoxVar": [prior_box_var.name],
                "TargetBox": [target_box.name],
                "BoxScore": [box_score.name]},
        outputs={"DecodeBox": [decoded.name],
                 "OutputAssignBox": [assigned.name]},
        attrs={"box_clip": box_clip})
    decoded.stop_gradient = assigned.stop_gradient = True
    return decoded, assigned


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype)
    probs = helper.create_variable_for_type_inference(scores.dtype)
    nums = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "generate_proposals",
        inputs={"Scores": [scores.name], "BboxDeltas": [bbox_deltas.name],
                "ImInfo": [im_info.name], "Anchors": [anchors.name],
                "Variances": [variances.name]},
        outputs={"RpnRois": [rois.name], "RpnRoiProbs": [probs.name],
                 "RpnRoisNum": [nums.name]},
        attrs={"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size, "eta": eta})
    rois.stop_gradient = probs.stop_gradient = nums.stop_gradient = True
    if return_rois_num:
        return rois, probs, nums
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    num_lvl = max_level - min_level + 1
    multi_rois = [helper.create_variable_for_type_inference(fpn_rois.dtype)
                  for _ in range(num_lvl)]
    restore = helper.create_variable_for_type_inference("int32")
    lvl_nums = [helper.create_variable_for_type_inference("int32")
                for _ in range(num_lvl)]
    inputs = {"FpnRois": [fpn_rois.name]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num.name]
    helper.append_op(
        "distribute_fpn_proposals", inputs=inputs,
        outputs={"MultiFpnRois": [v.name for v in multi_rois],
                 "RestoreIndex": [restore.name],
                 "MultiLevelRoIsNum": [v.name for v in lvl_nums]},
        attrs={"min_level": min_level, "max_level": max_level,
               "refer_level": refer_level, "refer_scale": refer_scale})
    for v in multi_rois + lvl_nums + [restore]:
        v.stop_gradient = True
    if rois_num is not None:
        return multi_rois, restore, lvl_nums
    return multi_rois, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None, name=None):
    helper = LayerHelper("collect_fpn_proposals", name=name)
    out = helper.create_variable_for_type_inference(multi_rois[0].dtype)
    nums = helper.create_variable_for_type_inference("int32")
    inputs = {"MultiLevelRois": [v.name for v in multi_rois],
              "MultiLevelScores": [v.name for v in multi_scores]}
    if rois_num_per_level is not None:
        inputs["MultiLevelRoisNum"] = [v.name for v in rois_num_per_level]
    helper.append_op("collect_fpn_proposals", inputs=inputs,
                     outputs={"FpnRois": [out.name], "RoisNum": [nums.name]},
                     attrs={"post_nms_topN": post_nms_top_n})
    out.stop_gradient = nums.stop_gradient = True
    if rois_num_per_level is not None:
        return out, nums
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input.name], "ROIs": [rois.name]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num.name]
    helper.append_op("roi_align", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale,
                            "sampling_ratio": sampling_ratio})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
             rois_num=None, name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input.name], "ROIs": [rois.name]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num.name]
    helper.append_op("roi_pool", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN training targets (ref detection.py rpn_target_assign /
    rpn_target_assign_op.cc).  Dense form: gt_boxes (B, G, 4)
    zero-padded; returns per-anchor tensors instead of LoD-compacted
    samples — (scores_pred, loc_pred, labels (B, A), bbox_targets
    (B, A, 4), bbox_inside_weights); multiply losses by the weights /
    mask on labels >= 0 to reproduce the sampled-minibatch loss."""
    helper = LayerHelper("rpn_target_assign")
    a = anchor_box.shape[0] if anchor_box.shape else None
    b = gt_boxes.shape[0] if gt_boxes.shape else None
    labels = helper.create_variable_for_type_inference("int32", (b, a))
    tgt = helper.create_variable_for_type_inference("float32", (b, a, 4))
    inw = helper.create_variable_for_type_inference("float32", (b, a, 4))
    outw = helper.create_variable_for_type_inference("float32",
                                                     (b, a, 4))
    inputs = {"Anchor": [anchor_box.name], "AnchorVar": [anchor_var.name],
              "GtBoxes": [gt_boxes.name]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd.name]
    if im_info is not None:
        inputs["ImInfo"] = [im_info.name]
    helper.append_op(
        "rpn_target_assign", inputs=inputs,
        outputs={"Labels": [labels.name], "BBoxTargets": [tgt.name],
                 "BBoxInsideWeights": [inw.name],
                 "BBoxOutsideWeights": [outw.name]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_fg_fraction": rpn_fg_fraction,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "use_random": use_random})
    for v in (labels, tgt, inw, outw):
        v.stop_gradient = True
    return cls_logits, bbox_pred, labels, tgt, inw


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box,
                            anchor_var, gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4):
    """RetinaNet training targets (ref detection.py
    retinanet_target_assign): labels carry the 1-based gt class; no
    subsampling (focal loss owns the imbalance).  Returns
    (cls_logits, bbox_pred, labels (B, A), bbox_targets, inside_w,
    fg_num (B, 1))."""
    helper = LayerHelper("retinanet_target_assign")
    a = anchor_box.shape[0] if anchor_box.shape else None
    b = gt_boxes.shape[0] if gt_boxes.shape else None
    labels = helper.create_variable_for_type_inference("int32", (b, a))
    tgt = helper.create_variable_for_type_inference("float32", (b, a, 4))
    inw = helper.create_variable_for_type_inference("float32", (b, a, 4))
    outw = helper.create_variable_for_type_inference("float32",
                                                     (b, a, 4))
    fg = helper.create_variable_for_type_inference("int32", (b, 1))
    inputs = {"Anchor": [anchor_box.name], "AnchorVar": [anchor_var.name],
              "GtBoxes": [gt_boxes.name], "GtLabels": [gt_labels.name]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd.name]
    if im_info is not None:
        inputs["ImInfo"] = [im_info.name]
    helper.append_op(
        "retinanet_target_assign", inputs=inputs,
        outputs={"Labels": [labels.name], "BBoxTargets": [tgt.name],
                 "BBoxInsideWeights": [inw.name],
                 "BBoxOutsideWeights": [outw.name],
                 "ForegroundNumber": [fg.name]},
        attrs={"positive_overlap": positive_overlap,
               "negative_overlap": negative_overlap})
    for v in (labels, tgt, inw, outw, fg):
        v.stop_gradient = True
    return cls_logits, bbox_pred, labels, tgt, inw, fg


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info=None, batch_size_per_im=512,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False,
                             is_cascade_rcnn=False):
    """Second-stage RoI sampling (ref detection.py
    generate_proposal_labels).  Dense form: rois (B, R, 4); returns
    (rois, labels (B, R) {-1,0,class}, bbox_targets (B, R, 4),
    inside_w, outside_w)."""
    if is_cls_agnostic or is_cascade_rcnn:
        raise NotImplementedError(
            "generate_proposal_labels: is_cls_agnostic / "
            "is_cascade_rcnn modes are not implemented in the dense "
            "redesign")
    helper = LayerHelper("generate_proposal_labels")
    b = rpn_rois.shape[0] if rpn_rois.shape else None
    r = rpn_rois.shape[1] if rpn_rois.shape else None
    rois = helper.create_variable_for_type_inference("float32",
                                                     (b, r, 4))
    labels = helper.create_variable_for_type_inference("int32", (b, r))
    tgt = helper.create_variable_for_type_inference("float32", (b, r, 4))
    inw = helper.create_variable_for_type_inference("float32", (b, r, 4))
    outw = helper.create_variable_for_type_inference("float32",
                                                     (b, r, 4))
    inputs = {"RpnRois": [rpn_rois.name], "GtClasses": [gt_classes.name],
              "GtBoxes": [gt_boxes.name]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd.name]
    if im_info is not None:
        inputs["ImInfo"] = [im_info.name]
    helper.append_op(
        "generate_proposal_labels", inputs=inputs,
        outputs={"Rois": [rois.name], "Labels": [labels.name],
                 "BBoxTargets": [tgt.name],
                 "BBoxInsideWeights": [inw.name],
                 "BBoxOutsideWeights": [outw.name]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi,
               "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "use_random": use_random})
    for v in (rois, labels, tgt, inw, outw):
        v.stop_gradient = True
    return rois, labels, tgt, inw, outw


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """EAST-style locality-aware NMS (ref detection.py
    locality_aware_nms): score-weighted merge of consecutive
    overlapping boxes, then standard NMS.  bboxes (N, M, 4), scores
    (N, C, M) -> (N, keep_top_k, 6)."""
    helper = LayerHelper("locality_aware_nms", name=name)
    n = bboxes.shape[0] if bboxes.shape else None
    out = helper.create_variable_for_type_inference(
        "float32", (n, keep_top_k, 6))
    helper.append_op(
        "locality_aware_nms",
        inputs={"BBoxes": [bboxes.name], "Scores": [scores.name]},
        outputs={"Out": [out.name]},
        attrs={"score_threshold": score_threshold,
               "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
               "nms_threshold": nms_threshold,
               "normalized": normalized, "nms_eta": nms_eta,
               "background_label": background_label})
    out.stop_gradient = True
    return out


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """RetinaNet inference head (ref detection.py
    retinanet_detection_output): per-FPN-level deltas/scores/anchors
    lists; decode + clip + class NMS -> (B, keep_top_k, 6)."""
    helper = LayerHelper("retinanet_detection_output")
    b = bboxes[0].shape[0] if bboxes[0].shape else None
    out = helper.create_variable_for_type_inference(
        "float32", (b, keep_top_k, 6))
    helper.append_op(
        "retinanet_detection_output",
        inputs={"BBoxes": [v.name for v in bboxes],
                "Scores": [v.name for v in scores],
                "Anchors": [v.name for v in anchors],
                "ImInfo": [im_info.name]},
        outputs={"Out": [out.name]},
        attrs={"score_threshold": score_threshold,
               "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
               "nms_threshold": nms_threshold, "nms_eta": nms_eta})
    out.stop_gradient = True
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    """Perspective roi crops for rotated-text heads (ref detection.py
    roi_perspective_transform).  Dense form: rois (B, R, 8) quads ->
    (B, R, C, out_h, out_w)."""
    helper = LayerHelper("roi_perspective_transform")
    b = input.shape[0] if input.shape else None
    r = rois.shape[1] if rois.shape else None
    c = input.shape[1] if input.shape else None
    out = helper.create_variable_for_type_inference(
        input.dtype, (b, r, c, transformed_height, transformed_width))
    helper.append_op(
        "roi_perspective_transform",
        inputs={"X": [input.name], "ROIs": [rois.name]},
        outputs={"Out": [out.name]},
        attrs={"transformed_height": transformed_height,
               "transformed_width": transformed_width,
               "spatial_scale": spatial_scale})
    return out


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         gt_boxes=None):
    """Mask-RCNN mask targets (ref detection.py generate_mask_labels).
    Dense contract: gt_segms (B, G, S, S) bitmaps registered to
    gt_boxes (B, G, 4); rois (B, R, 4); labels_int32 (B, R) from
    generate_proposal_labels.  Returns (mask_rois, roi_has_mask_int32,
    mask_int32 (B, R, num_classes*res*res), -1 = ignore)."""
    if gt_boxes is None:
        raise ValueError(
            "dense generate_mask_labels needs gt_boxes (B, G, 4): the "
            "bitmaps in gt_segms are registered to them")
    helper = LayerHelper("generate_mask_labels")
    b = rois.shape[0] if rois.shape else None
    r = rois.shape[1] if rois.shape else None
    mask_rois = helper.create_variable_for_type_inference(
        "float32", (b, r, 4))
    has_mask = helper.create_variable_for_type_inference("int32", (b, r))
    mask = helper.create_variable_for_type_inference(
        "int32", (b, r, num_classes * resolution * resolution))
    inputs = {"ImInfo": [im_info.name], "GtClasses": [gt_classes.name],
              "GtSegms": [gt_segms.name], "Rois": [rois.name],
              "LabelsInt32": [labels_int32.name],
              "GtBoxes": [gt_boxes.name]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd.name]
    helper.append_op(
        "generate_mask_labels", inputs=inputs,
        outputs={"MaskRois": [mask_rois.name],
                 "RoiHasMaskInt32": [has_mask.name],
                 "MaskInt32": [mask.name]},
        attrs={"num_classes": num_classes, "resolution": resolution})
    for v in (mask_rois, has_mask, mask):
        v.stop_gradient = True
    return mask_rois, has_mask, mask
