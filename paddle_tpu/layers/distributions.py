"""Probability distributions.

Reference parity: python/paddle/fluid/layers/distributions.py
(Uniform, Normal, Categorical, MultivariateNormalDiag subset).
"""
import math

from . import tensor as T
from . import ops
from .nn import elementwise_add, elementwise_sub, elementwise_mul, \
    elementwise_div, reduce_sum, softmax
from ..framework.program import Variable


def _as_var(v, like=None, dtype="float32"):
    if isinstance(v, Variable):
        return v
    return T.fill_constant([1], dtype, float(v))


class Distribution(object):
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low = _as_var(low)
        self.high = _as_var(high)

    def sample(self, shape, seed=0):
        u = ops.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        return elementwise_add(
            elementwise_mul(u, elementwise_sub(self.high, self.low)),
            self.low)

    def log_prob(self, value):
        rng = elementwise_sub(self.high, self.low)
        return ops.log(elementwise_div(T.ones([1]), rng)) + (value * 0.0)

    def entropy(self):
        return ops.log(elementwise_sub(self.high, self.low))


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_var(loc)
        self.scale = _as_var(scale)

    def sample(self, shape, seed=0):
        z = ops.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return elementwise_add(elementwise_mul(z, self.scale), self.loc)

    def log_prob(self, value):
        var = elementwise_mul(self.scale, self.scale)
        d = elementwise_sub(value, self.loc)
        return (elementwise_div(elementwise_mul(d, d), var) * (-0.5)) \
            - math.log(math.sqrt(2.0 * math.pi)) - ops.log(self.scale)

    def entropy(self):
        return ops.log(self.scale) + 0.5 * math.log(2.0 * math.pi * math.e)

    def kl_divergence(self, other):
        var_ratio = elementwise_div(self.scale, other.scale)
        var_ratio = elementwise_mul(var_ratio, var_ratio)
        t1 = elementwise_div(elementwise_sub(self.loc, other.loc),
                             other.scale)
        t1 = elementwise_mul(t1, t1)
        return (var_ratio + t1 - 1.0 - ops.log(var_ratio)) * 0.5


class Categorical(Distribution):
    def __init__(self, logits):
        self.logits = logits

    def sample(self, shape=None, seed=0):
        probs = softmax(self.logits)
        return ops.sampling_id(probs, seed=seed)

    def log_prob(self, value):
        """log P(value) for integer class labels: one-hot select on the
        log-softmax (reference distributions.py Categorical.log_prob)."""
        from .nn import log_softmax, one_hot
        logp = log_softmax(self.logits)
        depth = int(self.logits.shape[-1])
        sel = one_hot(value, depth)
        return reduce_sum(elementwise_mul(logp, sel), dim=-1)

    def entropy(self):
        from .nn import log_softmax
        p = softmax(self.logits)
        logp = log_softmax(self.logits)
        return reduce_sum(elementwise_mul(p, logp), dim=-1) * (-1.0)


class MultivariateNormalDiag(Distribution):
    """Multivariate normal with diagonal covariance (ref
    distributions.py MultivariateNormalDiag: loc (D,), scale diag (D, D);
    entropy and kl_divergence follow the reference formulas, which read
    `scale` as the covariance matrix)."""

    def __init__(self, loc, scale):
        self.loc = loc
        self.scale = scale          # (D, D) diagonal matrix

    def _diag(self):
        from .nn import reduce_sum, elementwise_mul
        from . import tensor as TT
        import numpy as np
        d = int(self.scale.shape[-1])
        eye = TT.assign(np.eye(d, dtype=np.float32))
        return reduce_sum(elementwise_mul(self.scale, eye), dim=-1)

    def entropy(self):
        """0.5 (D (1 + log 2pi) + log|Sigma|)."""
        from .nn import reduce_sum, scale as _sc
        from .ops import log
        d = int(self.scale.shape[-1])
        logdet = reduce_sum(log(self._diag()), dim=-1)
        half = float(0.5 * d * (1.0 + math.log(2.0 * math.pi)))
        return _sc(logdet, scale=0.5, bias=half)

    def kl_divergence(self, other):
        """KL(self || other): the reference treats `scale` as the
        COVARIANCE matrix — 0.5*(tr(S2^-1 S1) + (m2-m1)^T S2^-1 (m2-m1)
        - k + ln det S2/det S1) on the diagonals."""
        from .nn import (reduce_sum, elementwise_div, elementwise_sub,
                         scale as _sc)
        from .ops import log, square
        d1 = self._diag()
        d2 = other._diag()
        k = int(self.scale.shape[-1])
        tr = reduce_sum(elementwise_div(d1, d2), dim=-1)
        quad = reduce_sum(elementwise_div(
            square(elementwise_sub(other.loc, self.loc)), d2), dim=-1)
        ln_cov = elementwise_sub(reduce_sum(log(d2), dim=-1),
                                 reduce_sum(log(d1), dim=-1))
        inner = elementwise_add(elementwise_add(tr, quad), ln_cov)
        return _sc(inner, scale=0.5, bias=-0.5 * k)

