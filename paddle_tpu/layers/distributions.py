"""Probability distributions.

Reference parity: python/paddle/fluid/layers/distributions.py
(Uniform, Normal, Categorical, MultivariateNormalDiag subset).
"""
import math

from . import tensor as T
from . import ops
from .nn import elementwise_add, elementwise_sub, elementwise_mul, \
    elementwise_div, reduce_sum, softmax
from ..framework.program import Variable


def _as_var(v, like=None, dtype="float32"):
    if isinstance(v, Variable):
        return v
    return T.fill_constant([1], dtype, float(v))


class Distribution(object):
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low = _as_var(low)
        self.high = _as_var(high)

    def sample(self, shape, seed=0):
        u = ops.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        return elementwise_add(
            elementwise_mul(u, elementwise_sub(self.high, self.low)),
            self.low)

    def log_prob(self, value):
        rng = elementwise_sub(self.high, self.low)
        return ops.log(elementwise_div(T.ones([1]), rng)) + (value * 0.0)

    def entropy(self):
        return ops.log(elementwise_sub(self.high, self.low))


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_var(loc)
        self.scale = _as_var(scale)

    def sample(self, shape, seed=0):
        z = ops.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return elementwise_add(elementwise_mul(z, self.scale), self.loc)

    def log_prob(self, value):
        var = elementwise_mul(self.scale, self.scale)
        d = elementwise_sub(value, self.loc)
        return (elementwise_div(elementwise_mul(d, d), var) * (-0.5)) \
            - math.log(math.sqrt(2.0 * math.pi)) - ops.log(self.scale)

    def entropy(self):
        return ops.log(self.scale) + 0.5 * math.log(2.0 * math.pi * math.e)

    def kl_divergence(self, other):
        var_ratio = elementwise_div(self.scale, other.scale)
        var_ratio = elementwise_mul(var_ratio, var_ratio)
        t1 = elementwise_div(elementwise_sub(self.loc, other.loc),
                             other.scale)
        t1 = elementwise_mul(t1, t1)
        return (var_ratio + t1 - 1.0 - ops.log(var_ratio)) * 0.5


class Categorical(Distribution):
    def __init__(self, logits):
        self.logits = logits

    def sample(self, shape=None, seed=0):
        probs = softmax(self.logits)
        return ops.sampling_id(probs, seed=seed)

    def log_prob(self, value):
        """log P(value) for integer class labels: one-hot select on the
        log-softmax (reference distributions.py Categorical.log_prob)."""
        from .nn import log_softmax, one_hot
        logp = log_softmax(self.logits)
        depth = int(self.logits.shape[-1])
        sel = one_hot(value, depth)
        return reduce_sum(elementwise_mul(logp, sel), dim=-1)

    def entropy(self):
        from .nn import log_softmax
        p = softmax(self.logits)
        logp = log_softmax(self.logits)
        return reduce_sum(elementwise_mul(p, logp), dim=-1) * (-1.0)
