"""Learning-rate schedulers (static graph, in-graph computation).

Reference parity: python/paddle/fluid/layers/learning_rate_scheduler.py —
noam, exponential, natural_exp, inverse_time, polynomial, piecewise, cosine,
linear_lr_warmup. Same design: a persistable global-step var is incremented
each run and the LR is computed by ops inside the (jitted) step.
"""
import math

from ..layer_helper import LayerHelper
from .nn import autoincreased_step_counter, elementwise_div, elementwise_mul
from . import tensor
from . import ops
from .control_flow import less_than, piecewise_select
from .nn import where


def _decay_step_counter(begin=0):
    counter = autoincreased_step_counter(
        counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1)
    return tensor.cast(counter, "float32")


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    step = _decay_step_counter(begin=1)
    a = ops.pow(step, -0.5)
    b = step * (warmup_steps ** -1.5)
    lr = (d_model ** -0.5) * elementwise_min_var(a, b)
    return scale_lr(lr, learning_rate)


def elementwise_min_var(a, b):
    from .nn import elementwise_min
    return elementwise_min(a, b)


def scale_lr(lr, factor):
    from .nn import scale as scale_layer
    if factor == 1.0:
        return lr
    return scale_layer(lr, scale=float(factor))


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return scale_lr(ops.exp(div * math.log(decay_rate)), learning_rate)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return scale_lr(ops.exp(div * (-decay_rate)), learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    denom = div * decay_rate + 1.0
    one = tensor.fill_constant([1], "float32", 1.0)
    return scale_lr(elementwise_div(one, denom), learning_rate)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _decay_step_counter()
    if cycle:
        div = ops.ceil(step / float(decay_steps))
        from .nn import elementwise_max
        one = tensor.fill_constant([1], "float32", 1.0)
        div = elementwise_max(div, one)
        decay_steps_var = div * float(decay_steps)
        frac = step / decay_steps_var
    else:
        from .nn import elementwise_min
        cap = tensor.fill_constant([1], "float32", float(decay_steps))
        step = elementwise_min(step, cap)
        frac = step / float(decay_steps)
    base = (1.0 - frac) ** power if power == 1.0 else None
    one = tensor.fill_constant([1], "float32", 1.0)
    pw = ops.pow(one - frac, factor=power)
    return pw * (learning_rate - end_learning_rate) + end_learning_rate


def piecewise_decay(boundaries, values):
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries)+1")
    step = autoincreased_step_counter(counter_name="@LR_DECAY_COUNTER@",
                                      begin=0, step=1)
    return piecewise_select(tensor.cast(step, "float32"),
                            [float(b) for b in boundaries],
                            [float(v) for v in values])


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    epoch = ops.floor(step / float(step_each_epoch))
    return learning_rate * 0.5 * (ops.cos(epoch * (math.pi / epochs)) + 1.0)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _decay_step_counter()
    from ..framework.program import Variable
    if not isinstance(learning_rate, Variable):
        learning_rate = tensor.fill_constant([1], "float32",
                                             float(learning_rate))
    warm = float(start_lr) + (float(end_lr) - float(start_lr)) * \
        (step / float(warmup_steps))
    return where(less_than(step, float(warmup_steps)), warm, learning_rate)
