"""RNN layers.

Reference parity: python/paddle/fluid/layers/rnn.py + nn.py dynamic_lstm /
dynamic_gru / gru_unit / lstm_unit. Batch-major dense layout (N, T, ...),
lax.scan under the hood (ops/rnn_ops.py) — BPTT via vjp.
"""
from ..layer_helper import LayerHelper
from .nn import fc
from ..initializer import ConstantInitializer


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """input: (N, T, 4*hidden) pre-projected (same contract as the reference
    dynamic_lstm); size = 4*hidden."""
    helper = LayerHelper("dynamic_lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name, dtype=dtype)
    hidden = size // 4
    w = helper.create_parameter(helper.param_attr, shape=[hidden, 4 * hidden],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[4 * hidden],
                                dtype=dtype, is_bias=True)
    n = input.shape[0]
    t = input.shape[1]
    hidden_out = helper.create_variable_for_type_inference(
        dtype, (n, t, hidden))
    cell_out = helper.create_variable_for_type_inference(dtype,
                                                         (n, t, hidden))
    last_h = helper.create_variable_for_type_inference(dtype, (n, hidden))
    last_c = helper.create_variable_for_type_inference(dtype, (n, hidden))
    inputs = {"Input": [input.name], "Weight": [w.name], "Bias": [b.name]}
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    if c_0 is not None:
        inputs["C0"] = [c_0.name]
    helper.append_op(
        "lstm_seq", inputs=inputs,
        outputs={"Hidden": [hidden_out.name], "Cell": [cell_out.name],
                 "LastH": [last_h.name], "LastC": [last_c.name]},
        attrs={"is_reverse": is_reverse, "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return hidden_out, cell_out


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32"):
    """input: (N, T, 3*size) pre-projected; returns hidden (N, T, size)."""
    helper = LayerHelper("dynamic_gru", param_attr=param_attr,
                         bias_attr=bias_attr, dtype=dtype)
    w = helper.create_parameter(helper.param_attr, shape=[size, 3 * size],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[3 * size],
                                dtype=dtype, is_bias=True)
    n, t = input.shape[0], input.shape[1]
    hidden_out = helper.create_variable_for_type_inference(dtype, (n, t, size))
    last_h = helper.create_variable_for_type_inference(dtype, (n, size))
    inputs = {"Input": [input.name], "Weight": [w.name], "Bias": [b.name]}
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    helper.append_op(
        "gru_seq", inputs=inputs,
        outputs={"Hidden": [hidden_out.name], "LastH": [last_h.name]},
        attrs={"is_reverse": is_reverse, "gate_activation": gate_activation,
               "activation": candidate_activation})
    return hidden_out


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    hidden_dim = size // 3
    w = helper.create_parameter(helper.param_attr,
                                shape=[hidden_dim, 3 * hidden_dim],
                                dtype=input.dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[3 * hidden_dim],
                                dtype=input.dtype, is_bias=True)
    n = input.shape[0]
    out_h = helper.create_variable_for_type_inference(input.dtype,
                                                      (n, hidden_dim))
    gate = helper.create_variable_for_type_inference(input.dtype)
    reset_h = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "gru_unit",
        inputs={"Input": [input.name], "HiddenPrev": [hidden.name],
                "Weight": [w.name], "Bias": [b.name]},
        outputs={"Hidden": [out_h.name], "Gate": [gate.name],
                 "ResetHiddenPrev": [reset_h.name]},
        attrs={"activation": activation, "gate_activation": gate_activation})
    return out_h, reset_h, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step built from fc + elementwise ops (reference lstm_unit)."""
    from .nn import elementwise_add
    from .ops import sigmoid, tanh
    from .tensor import concat
    from .nn import split as split_layer
    size = cell_t_prev.shape[-1]
    concat_in = concat([x_t, hidden_t_prev], axis=-1)
    gates = fc(concat_in, size=4 * size, param_attr=param_attr,
               bias_attr=bias_attr)
    i, f, c_hat, o = split_layer(gates, 4, dim=-1)
    f = elementwise_add(f, _const_like(f, forget_bias)) if forget_bias else f
    from .nn import elementwise_mul
    c = elementwise_add(elementwise_mul(sigmoid(f), cell_t_prev),
                        elementwise_mul(sigmoid(i), tanh(c_hat)))
    h = elementwise_mul(sigmoid(o), tanh(c))
    return h, c


def _const_like(v, value):
    from .tensor import fill_constant
    return fill_constant([1], v.dtype, value)
