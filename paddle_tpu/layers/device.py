"""fluid.layers.device parity (ref python/paddle/fluid/layers/device.py:
get_places, deprecated even in the reference)."""
from ..annotations import deprecated

__all__ = ["get_places"]


@deprecated(since="0.15.0", instead="ParallelExecutor / CompiledProgram")
def get_places(device_count=None, device_type=None):
    import jax
    devs = jax.devices() if device_type is None else \
        [d for d in jax.devices() if d.platform == device_type]
    if device_count:
        devs = devs[:device_count]
    return devs
