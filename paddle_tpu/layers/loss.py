"""Loss layers.

Reference parity: python/paddle/fluid/layers/loss.py.
"""
from ..layer_helper import LayerHelper


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    shape = tuple(input.shape[:-1]) + (1,) if input.shape else None
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("cross_entropy",
                     inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_variable_for_type_inference(logits.dtype,
                                                        logits.shape)
    loss_shape = None
    if logits.shape is not None:
        loss_shape = list(logits.shape)
        loss_shape[axis] = 1
        loss_shape = tuple(loss_shape)
    loss = helper.create_variable_for_type_inference(logits.dtype, loss_shape)
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": [logits.name], "Label": [label.name]},
        outputs={"Softmax": [softmax.name], "Loss": [loss.name]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index,
               "axis": axis})
    if return_softmax:
        return loss, softmax
    return loss


def fused_mlm_head_loss(hidden, weight, label, bias=None,
                        cast_bf16=False):
    """Fused LM/MLM head: ``hidden (T, D) @ weight^T (+ bias)`` ->
    per-token softmax CE loss ``(T, 1)`` in ONE op, so the
    ``[tokens, vocab]`` logits can skip HBM entirely when
    ``BuildStrategy.use_pallas={"fused_mlm_head_loss"}`` routes it to
    the Pallas kernel (ops/pallas/blockwise_ce). ``weight`` is the
    (V, D) tied embedding table; ``cast_bf16`` runs the projection in
    bf16 with f32 accumulation (models/bert._mlm_decode's MXU trick).
    The XLA fallback computes the identical matmul + CE chain, so
    wiring a model head through this layer is loss-curve-neutral with
    Pallas off."""
    helper = LayerHelper("fused_mlm_head_loss")
    t = hidden.shape[0] if hidden.shape else None
    loss = helper.create_variable_for_type_inference(
        "float32", (t, 1) if t is not None else None)
    inputs = {"Hidden": [hidden.name], "Weight": [weight.name],
              "Label": [label.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    helper.append_op(
        "fused_mlm_head_loss", inputs=inputs,
        outputs={"Loss": [loss.name]},
        attrs={"cast_bf16": bool(cast_bf16)})
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("square_error_cost",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [out.name]})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x.name], "Label": [label.name]},
                     outputs={"Out": [out.name]},
                     attrs={"ignore_index": ignore_index,
                            "normalize": normalize})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    loss = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [loss.name], "Diff": [diff.name]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    loss = helper.create_variable_for_type_inference(input.dtype)
    resid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("huber_loss",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [loss.name], "Residual": [resid.name]},
                     attrs={"delta": float(delta)})
    return loss


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    loss = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("log_loss",
                     inputs={"Predicted": [input.name],
                             "Labels": [label.name]},
                     outputs={"Loss": [loss.name]},
                     attrs={"epsilon": float(epsilon)})
    return loss


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kldiv_loss",
                     inputs={"X": [x.name], "Target": [target.name]},
                     outputs={"Loss": [loss.name]},
                     attrs={"reduction": reduction})
    return loss


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    shape = (input.shape[0], 1) if input.shape else None
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("bpr_loss",
                     inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype, left.shape)
    act = helper.create_variable_for_type_inference(left.dtype, left.shape)
    helper.append_op("margin_rank_loss",
                     inputs={"X1": [left.name], "X2": [right.name],
                             "Label": [label.name]},
                     outputs={"Out": [out.name], "Activated": [act.name]},
                     attrs={"margin": float(margin)})
    return out


def mse_loss(input, label):
    helper = LayerHelper("mse_loss")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("mse_loss",
                     inputs={"Input": [input.name], "Label": [label.name]},
                     outputs={"Out": [out.name]})
    return out


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss (reference: python/paddle/fluid/layers/loss.py warpctc,
    paddle/fluid/operators/warpctc_op.cc). Dense/padded calling convention:
    ``input`` (T, N, C) time-major unnormalized logits, ``label`` (N, Lmax)
    int labels, with per-example ``input_length``/``label_length``. Returns
    (N, 1) loss. Softmax is applied inside the op, matching warp-ctc.
    """
    helper = LayerHelper("warpctc")
    n = input.shape[1] if input.shape is not None else None
    out = helper.create_variable_for_type_inference(
        input.dtype, (n, 1) if n is not None else None)
    inputs = {"Logits": [input.name], "Label": [label.name]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length.name]
    if label_length is not None:
        inputs["LabelLength"] = [label_length.name]
    helper.append_op("warpctc", inputs=inputs, outputs={"Loss": [out.name]},
                     attrs={"blank": int(blank),
                            "norm_by_times": bool(norm_by_times)})
    return out


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (ref layers/loss.py:1260 rank_loss):
    sigmoid CE on (left - right) with label in {0, 1}."""
    from .nn import elementwise_sub
    diff = elementwise_sub(left, right)
    return sigmoid_cross_entropy_with_logits(diff, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (ref layers/loss.py:1588): soft-label softmax CE
    on the anchor/positive similarity matrix + Beta*l2_reg embedding L2."""
    from .nn import (reshape, expand, transpose, matmul, reduce_sum,
                     reduce_mean, elementwise_div, elementwise_add, scale,
                     cast)
    from .ops import square
    from .control_flow import equal
    beta = 0.25
    n = labels.shape[0]
    lab = reshape(labels, [n, 1])
    lab = expand(lab, [1, n])
    same = cast(equal(lab, transpose(lab, [1, 0])), "float32")
    soft = elementwise_div(same, reduce_sum(same, dim=1, keep_dim=True))
    l2 = scale(elementwise_add(
        reduce_mean(reduce_sum(square(anchor), dim=1)),
        reduce_mean(reduce_sum(square(positive), dim=1))),
        scale=beta * float(l2_reg))
    sim = matmul(anchor, positive, transpose_y=True)
    ce = softmax_with_cross_entropy(sim, soft, soft_label=True)
    return elementwise_add(reduce_mean(ce), l2)


def teacher_student_sigmoid_loss(input, label,
                                 soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """CTR distillation loss (ref layers/loss.py:1437 +
    teacher_student_sigmoid_loss_op.h label-encoding cases)."""
    from .nn import clip
    x = clip(input, soft_max_lower_bound, soft_max_up_bound)
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("teacher_student_sigmoid_loss",
                     inputs={"X": [x.name], "Label": [label.name]},
                     outputs={"Y": [out.name]})
    return out


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """Class-center clustering loss with in-graph center updates (ref
    layers/loss.py:53 center_loss + center_loss_op.h)."""
    from .. import initializer as init_mod
    from . import tensor as T
    helper = LayerHelper("center_loss", param_attr=param_attr)
    dim = input.shape[-1]
    centers = helper.create_parameter(
        attr=helper.param_attr,
        shape=[num_classes, dim], dtype=str(input.dtype),
        default_initializer=init_mod.Constant(0.0))
    centers.trainable = False        # updated by the op, not the optimizer
    rate = T.fill_constant([1], "float32", float(alpha))
    loss = helper.create_variable_for_type_inference(input.dtype)
    diff = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "center_loss",
        inputs={"X": [input.name], "Label": [label.name],
                "Centers": [centers.name],
                "CenterUpdateRate": [rate.name]},
        outputs={"Loss": [loss.name], "SampleCenterDiff": [diff.name],
                 "CentersOut": [centers.name]},
        attrs={"update_center": bool(update_center)})
    return loss


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance (ref layers/loss.py:352), dense (N, T) ids +
    optional lengths; ignored_tokens is not supported (filter host-side)."""
    if ignored_tokens:
        raise NotImplementedError(
            "edit_distance ignored_tokens: filter tokens in the data "
            "pipeline (dense/static design)")
    helper = LayerHelper("edit_distance")
    inputs = {"Hyps": [input.name], "Refs": [label.name]}
    if input_length is not None:
        inputs["HypsLength"] = [input_length.name]
    if label_length is not None:
        inputs["RefsLength"] = [label_length.name]
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op("edit_distance", inputs=inputs,
                     outputs={"Out": [out.name],
                              "SequenceNum": [seq_num.name]},
                     attrs={"normalized": bool(normalized)})
    return out, seq_num


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss with its own weight/bias params
    (ref layers/loss.py:624 nce). custom_dist is unsupported (uniform /
    log_uniform samplers only)."""
    if custom_dist is not None:
        raise NotImplementedError("nce custom_dist sampler")
    if sample_weight is not None:
        raise NotImplementedError("nce sample_weight (weight examples in "
                                  "the data pipeline instead)")
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=str(input.dtype))
    inputs = {"Input": [input.name], "Label": [label.name],
              "Weight": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[num_total_classes, 1],
                                    dtype=str(input.dtype), is_bias=True)
        inputs["Bias"] = [b.name]
    cost = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("nce", inputs=inputs,
                     outputs={"Cost": [cost.name]},
                     attrs={"num_total_classes": int(num_total_classes),
                            "num_neg_samples": int(num_neg_samples),
                            "sampler": sampler})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None,
             is_custom=False, is_sparse=False):
    """Hierarchical sigmoid over the default complete binary tree (ref
    layers/loss.py:838 hsigmoid). Custom trees (path_table/path_code) are
    unsupported."""
    if is_custom or path_table is not None or path_code is not None:
        raise NotImplementedError("hsigmoid custom trees")
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_classes - 1, dim],
                                dtype=str(input.dtype))
    inputs = {"X": [input.name], "Label": [label.name], "W": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[num_classes - 1, 1],
                                    dtype=str(input.dtype), is_bias=True)
        inputs["Bias"] = [b.name]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": [out.name], "PreOut": [pre.name]},
                     attrs={"num_classes": int(num_classes)})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Softmax CE over true + sampled classes (ref layers/loss.py:999).
    seed is ignored: sampling uses the framework's deterministic per-op
    PRNG (framework/trace.py)."""
    if use_customized_samples:
        raise NotImplementedError("customized samples")
    if num_true != 1:
        raise NotImplementedError("sampled softmax with num_true != 1")
    if not remove_accidental_hits:
        raise NotImplementedError(
            "remove_accidental_hits=False (the kernel always masks "
            "accidental hits)")
    helper = LayerHelper("sampled_softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("sampled_softmax_with_cross_entropy",
                     inputs={"Logits": [logits.name],
                             "Label": [label.name]},
                     outputs={"Loss": [loss.name]},
                     attrs={"num_samples": int(num_samples)})
    return loss
