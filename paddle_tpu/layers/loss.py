"""Loss layers.

Reference parity: python/paddle/fluid/layers/loss.py.
"""
from ..layer_helper import LayerHelper


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    shape = tuple(input.shape[:-1]) + (1,) if input.shape else None
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("cross_entropy",
                     inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_variable_for_type_inference(logits.dtype,
                                                        logits.shape)
    loss_shape = None
    if logits.shape is not None:
        loss_shape = list(logits.shape)
        loss_shape[axis] = 1
        loss_shape = tuple(loss_shape)
    loss = helper.create_variable_for_type_inference(logits.dtype, loss_shape)
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": [logits.name], "Label": [label.name]},
        outputs={"Softmax": [softmax.name], "Loss": [loss.name]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index,
               "axis": axis})
    if return_softmax:
        return loss, softmax
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("square_error_cost",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [out.name]})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x.name], "Label": [label.name]},
                     outputs={"Out": [out.name]},
                     attrs={"ignore_index": ignore_index,
                            "normalize": normalize})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    loss = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [loss.name], "Diff": [diff.name]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    loss = helper.create_variable_for_type_inference(input.dtype)
    resid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("huber_loss",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [loss.name], "Residual": [resid.name]},
                     attrs={"delta": float(delta)})
    return loss


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    loss = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("log_loss",
                     inputs={"Predicted": [input.name],
                             "Labels": [label.name]},
                     outputs={"Loss": [loss.name]},
                     attrs={"epsilon": float(epsilon)})
    return loss


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kldiv_loss",
                     inputs={"X": [x.name], "Target": [target.name]},
                     outputs={"Loss": [loss.name]},
                     attrs={"reduction": reduction})
    return loss


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    shape = (input.shape[0], 1) if input.shape else None
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("bpr_loss",
                     inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype, left.shape)
    act = helper.create_variable_for_type_inference(left.dtype, left.shape)
    helper.append_op("margin_rank_loss",
                     inputs={"X1": [left.name], "X2": [right.name],
                             "Label": [label.name]},
                     outputs={"Out": [out.name], "Activated": [act.name]},
                     attrs={"margin": float(margin)})
    return out


def mse_loss(input, label):
    helper = LayerHelper("mse_loss")
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("mse_loss",
                     inputs={"Input": [input.name], "Label": [label.name]},
                     outputs={"Out": [out.name]})
    return out


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss (reference: python/paddle/fluid/layers/loss.py warpctc,
    paddle/fluid/operators/warpctc_op.cc). Dense/padded calling convention:
    ``input`` (T, N, C) time-major unnormalized logits, ``label`` (N, Lmax)
    int labels, with per-example ``input_length``/``label_length``. Returns
    (N, 1) loss. Softmax is applied inside the op, matching warp-ctc.
    """
    helper = LayerHelper("warpctc")
    n = input.shape[1] if input.shape is not None else None
    out = helper.create_variable_for_type_inference(
        input.dtype, (n, 1) if n is not None else None)
    inputs = {"Logits": [input.name], "Label": [label.name]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length.name]
    if label_length is not None:
        inputs["LabelLength"] = [label_length.name]
    helper.append_op("warpctc", inputs=inputs, outputs={"Loss": [out.name]},
                     attrs={"blank": int(blank),
                            "norm_by_times": bool(norm_by_times)})
    return out
