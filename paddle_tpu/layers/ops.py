"""Auto-generated elementwise / activation layers.

Reference parity: python/paddle/fluid/layers/ops.py +
layer_function_generator.py 'generate_layer_fn' — same trick: one factory
per registered unary op.
"""
import sys

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softplus",
    "softsign", "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin",
    "acos", "asin", "atan", "round", "reciprocal", "square", "relu",
    "gelu", "erf", "sign", "log", "log1p", "expm1", "silu", "mish",
]


def _make_unary(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
        helper.append_op(op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]})
        return out
    layer.__name__ = op_type
    layer.__doc__ = "TPU kernel for fluid.layers.%s" % op_type
    return layer


_mod = sys.modules[__name__]
for _op in _UNARY_OPS:
    setattr(_mod, _op, _make_unary(_op))


def _attr_unary(op_type, attr_names_defaults):
    def layer(x, *args, **kwargs):
        attrs = {}
        for (aname, default), val in zip(
                attr_names_defaults,
                list(args) + [None] * len(attr_names_defaults)):
            v = kwargs.get(aname, val)
            attrs[aname] = default if v is None else v
        helper = LayerHelper(op_type, name=kwargs.get("name"))
        out = helper.create_variable_for_type_inference(x.dtype, x.shape)
        helper.append_op(op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out
    layer.__name__ = op_type
    return layer


relu6 = _attr_unary("relu6", [("threshold", 6.0)])
leaky_relu = _attr_unary("leaky_relu", [("alpha", 0.02)])
elu = _attr_unary("elu", [("alpha", 1.0)])
swish = _attr_unary("swish", [("beta", 1.0)])
hard_sigmoid = _attr_unary("hard_sigmoid", [("slope", 0.2), ("offset", 0.5)])
hard_swish = _attr_unary("hard_swish", [("threshold", 6.0), ("scale", 6.0),
                                        ("offset", 3.0)])
hard_shrink = _attr_unary("hard_shrink", [("threshold", 0.5)])
softshrink = _attr_unary("softshrink", [("lambda", 0.5)])
thresholded_relu = _attr_unary("thresholded_relu", [("threshold", 1.0)])
brelu = _attr_unary("brelu", [("t_min", 0.0), ("t_max", 24.0)])
soft_relu = _attr_unary("soft_relu", [("threshold", 40.0)])
stanh = _attr_unary("stanh", [("scale_a", 0.67), ("scale_b", 1.7159)])
selu = _attr_unary("selu", [("scale", 1.0507009873554805),
                            ("alpha", 1.6732632423543772)])


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("pow", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"factor": factor})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("uniform_random", outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "min": min, "max": max, "seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype, tuple(shape))
    helper.append_op("gaussian_random", outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "mean": mean, "std": std, "seed": seed})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("sampling_id", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"seed": seed})
    out.stop_gradient = True
    return out
