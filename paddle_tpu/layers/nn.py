"""Neural network layers.

Reference parity: python/paddle/fluid/layers/nn.py — same signatures so
fluid model definitions port verbatim; each appends ops whose kernels are
pure JAX (ops/), fused by XLA at Executor compile time.
"""
from ..layer_helper import LayerHelper
from ..framework.program import Variable
from ..initializer import ConstantInitializer, XavierInitializer
from . import tensor as tensor_layers


def _single(helper, op_type, x, attrs=None, shape=None, extra_inputs=None,
            out_slot="Out", dtype=None):
    out = helper.create_variable_for_type_inference(dtype or x.dtype, shape)
    inputs = {"X": [x.name]}
    if extra_inputs:
        inputs.update(extra_inputs)
    helper.append_op(op_type, inputs=inputs, outputs={out_slot: [out.name]},
                     attrs=attrs or {})
    return out


# ---------------------------------------------------------------------------
# fc / embedding
# ---------------------------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, p_attr in helper.iter_inputs_and_params():
        in_shape = input_var.shape
        param_shape = [int(__import__("math").prod(
            in_shape[num_flatten_dims:]))] + [size]
        w = helper.create_parameter(p_attr, shape=param_shape, dtype=dtype)
        out_shape = tuple(in_shape[:num_flatten_dims]) + (size,)
        tmp = helper.create_variable_for_type_inference(dtype, out_shape)
        helper.append_op(
            "mul", inputs={"X": [input_var.name], "Y": [w.name]},
            outputs={"Out": [tmp.name]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(
            dtype, mul_results[0].shape)
        helper.append_op("sum", inputs={"X": [m.name for m in mul_results]},
                         outputs={"Out": [pre_bias.name]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    helper = LayerHelper("embedding", param_attr=param_attr, dtype=dtype)
    w = helper.create_parameter(helper.param_attr, shape=list(size),
                                dtype=dtype)
    if is_distributed and getattr(w, "sharding", None) is None:
        # pserver-equivalent: row-shard the table over the mp axis so
        # CompiledProgram gives it a NamedSharding and XLA keeps
        # lookups/updates on the owner shard (distributed/sharded_embedding)
        w.sharding = ("mp", None)
    in_shape = input.shape or (-1,)
    out_shape = tuple(in_shape[:-1] if in_shape[-1] == 1 else in_shape) + \
        (size[1],)
    tmp = helper.create_variable_for_type_inference(dtype, out_shape)
    padding_idx = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(
        "lookup_table",
        inputs={"W": [w.name], "Ids": [input.name]},
        outputs={"Out": [tmp.name]},
        attrs={"is_sparse": is_sparse, "padding_idx": padding_idx,
               "is_distributed": is_distributed})
    return tmp


# ---------------------------------------------------------------------------
# conv / pool / norm
# ---------------------------------------------------------------------------

def _conv_out_size(i, k, p, s, d=1):
    if i in (None, -1):
        return -1
    ke = d * (k - 1) + 1
    return (i + 2 * p - ke) // s + 1


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    groups = groups or 1
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) \
        else list(dilation)
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    import math as _m
    std = (2.0 / (filter_size[0] * filter_size[1] * num_channels)) ** 0.5
    from ..initializer import NormalInitializer
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, std))
    oh = _conv_out_size(input.shape[2], filter_size[0], padding[0], stride[0],
                        dilation[0])
    ow = _conv_out_size(input.shape[3], filter_size[1], padding[1], stride[1],
                        dilation[1])
    out_shape = (input.shape[0], num_filters, oh, ow)
    pre_bias = helper.create_variable_for_type_inference(dtype, out_shape)
    helper.append_op(
        "conv2d", inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", input=input,
                         param_attr=param_attr, bias_attr=bias_attr, act=act,
                         name=name)
    dtype = helper.input_dtype()
    groups = groups or 1
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) \
        else list(dilation)
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    out_shape = None
    osz = None
    if output_size is not None:
        osz = [output_size, output_size] if isinstance(output_size, int) \
            else list(output_size)
        out_shape = (input.shape[0], num_filters, osz[0], osz[1])
    elif input.shape is not None and filter_size is not None and \
            None not in input.shape[2:]:
        spatial = [
            (input.shape[2 + i] - 1) * stride[i] - 2 * padding[i] +
            dilation[i] * (filter_size[i] - 1) + 1
            if input.shape[2 + i] != -1 else -1
            for i in range(2)]
        out_shape = (input.shape[0], num_filters) + tuple(spatial)
    pre_bias = helper.create_variable_for_type_inference(dtype, out_shape)
    helper.append_op(
        "conv2d_transpose",
        inputs={"Input": [input.name], "Filter": [w.name]},
        outputs={"Output": [pre_bias.name]},
        attrs={"strides": stride, "paddings": padding, "dilations": dilation,
               "groups": groups, "output_size": osz})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    helper = LayerHelper("pool2d", name=name)
    pool_size = [pool_size, pool_size] if isinstance(pool_size, int) \
        else list(pool_size)
    pool_stride = [pool_stride, pool_stride] \
        if isinstance(pool_stride, int) else list(pool_stride)
    pool_padding = [pool_padding, pool_padding] \
        if isinstance(pool_padding, int) else list(pool_padding)
    if global_pooling:
        shape = (input.shape[0], input.shape[1], 1, 1)
    else:
        oh = _conv_out_size(input.shape[2], pool_size[0], pool_padding[0],
                            pool_stride[0])
        ow = _conv_out_size(input.shape[3], pool_size[1], pool_padding[1],
                            pool_stride[1])
        shape = (input.shape[0], input.shape[1], oh, ow)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op(
        "pool2d", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "strides": pool_stride, "paddings": pool_padding,
               "global_pooling": global_pooling, "exclusive": exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    pool_size = [pool_size, pool_size] if isinstance(pool_size, int) \
        else list(pool_size)
    shape = (input.shape[0], input.shape[1], pool_size[0], pool_size[1])
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op(
        "pool2d", inputs={"X": [input.name]}, outputs={"Out": [out.name]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "adaptive": True})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=
               False, use_global_stats=False):
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = "float32"  # stats in fp32 even for bf16 activations
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        helper.param_attr, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    from ..framework import unique_name as _un
    mean = helper.create_or_get_global_variable(
        name=moving_mean_name or _un.generate(helper.name + ".mean"),
        dtype=dtype, shape=(c,), persistable=True)
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = helper.create_or_get_global_variable(
        name=moving_variance_name or _un.generate(helper.name + ".var"),
        dtype=dtype, shape=(c,), persistable=True)
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))

    saved_mean = helper.create_variable_for_type_inference(dtype, (c,))
    saved_var = helper.create_variable_for_type_inference(dtype, (c,))
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op(
        "batch_norm",
        inputs={"X": [input.name], "Scale": [scale.name],
                "Bias": [bias.name], "Mean": [mean.name],
                "Variance": [variance.name]},
        outputs={"Y": [out.name], "MeanOut": [mean.name],
                 "VarianceOut": [variance.name],
                 "SavedMean": [saved_mean.name],
                 "SavedVariance": [saved_var.name]},
        attrs={"momentum": momentum, "epsilon": epsilon,
               "is_test": is_test, "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.kwargs.get("dtype", input.dtype)
    import math as _m
    norm_size = int(_m.prod(input.shape[begin_norm_axis:]))
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(
            helper.param_attr, shape=[norm_size], dtype="float32",
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(helper.bias_attr, shape=[norm_size],
                                    dtype="float32", is_bias=True)
        inputs["Bias"] = [b.name]
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    mean = helper.create_variable_for_type_inference(
        "float32", input.shape[:begin_norm_axis])
    var = helper.create_variable_for_type_inference(
        "float32", input.shape[:begin_norm_axis])
    helper.append_op(
        "layer_norm", inputs=inputs,
        outputs={"Y": [out.name], "Mean": [mean.name],
                 "Variance": [var.name]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    inputs = {"X": [input.name]}
    if param_attr is not False:
        s = helper.create_parameter(
            helper.param_attr, shape=[c], dtype="float32",
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s.name]
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[c],
                                    dtype="float32", is_bias=True)
        inputs["Bias"] = [b.name]
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    mean = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    helper.append_op("group_norm", inputs=inputs,
                     outputs={"Y": [out.name], "Mean": [mean.name],
                              "Variance": [var.name]},
                     attrs={"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    c = input.shape[1]
    inputs = {"X": [input.name]}
    if param_attr is not False:
        s = helper.create_parameter(
            helper.param_attr, shape=[c], dtype="float32",
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s.name]
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[c],
                                    dtype="float32", is_bias=True)
        inputs["Bias"] = [b.name]
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    sm = helper.create_variable_for_type_inference("float32")
    sv = helper.create_variable_for_type_inference("float32")
    helper.append_op("instance_norm", inputs=inputs,
                     outputs={"Y": [out.name], "SavedMean": [sm.name],
                              "SavedVariance": [sv.name]},
                     attrs={"epsilon": epsilon})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("l2_normalize", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Norm": [norm.name]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Ref nn.py:3156 / spectral_norm_op.h: weight / sigma_max via power
    iteration; U and V iterates persist across steps (batch_norm-style
    running state)."""
    helper = LayerHelper("spectral_norm", name=name)
    import math as _m
    shape = weight.shape
    perm_h = shape[dim]
    perm_w = int(_m.prod(shape)) // perm_h
    from ..framework import unique_name as _un
    from ..initializer import NormalInitializer
    u = helper.create_or_get_global_variable(
        name=_un.generate(helper.name + ".u"), dtype="float32",
        shape=(perm_h,), persistable=True)
    helper.set_variable_initializer(u, NormalInitializer(0.0, 1.0))
    v = helper.create_or_get_global_variable(
        name=_un.generate(helper.name + ".v"), dtype="float32",
        shape=(perm_w,), persistable=True)
    helper.set_variable_initializer(v, NormalInitializer(0.0, 1.0))
    out = helper.create_variable_for_type_inference(weight.dtype,
                                                    weight.shape)
    helper.append_op(
        "spectral_norm",
        inputs={"Weight": [weight.name], "U": [u.name], "V": [v.name]},
        outputs={"Out": [out.name], "UOut": [u.name], "VOut": [v.name]},
        attrs={"dim": int(dim), "power_iters": int(power_iters),
               "eps": float(eps)})
    return out


# ---------------------------------------------------------------------------
# dropout / elementwise / matmul
# ---------------------------------------------------------------------------

def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    mask = helper.create_variable_for_type_inference("uint8", x.shape)
    helper.append_op(
        "dropout", inputs={"X": [x.name]},
        outputs={"Out": [out.name], "Mask": [mask.name]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "seed": seed or 0,
               "dropout_implementation": dropout_implementation})
    return out


def _elementwise_layer(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, act=act, name=name)
        shape = x.shape if (x.shape is not None and y.shape is not None and
                            len(x.shape) >= len(y.shape)) else y.shape
        out = helper.create_variable_for_type_inference(x.dtype, shape)
        helper.append_op(op_type, inputs={"X": [x.name], "Y": [y.name]},
                         outputs={"Out": [out.name]}, attrs={"axis": axis})
        return helper.append_activation(out)
    layer.__name__ = op_type
    return layer


elementwise_add = _elementwise_layer("elementwise_add")
elementwise_sub = _elementwise_layer("elementwise_sub")
elementwise_mul = _elementwise_layer("elementwise_mul")
elementwise_div = _elementwise_layer("elementwise_div")
elementwise_max = _elementwise_layer("elementwise_max")
elementwise_min = _elementwise_layer("elementwise_min")
elementwise_pow = _elementwise_layer("elementwise_pow")
elementwise_mod = _elementwise_layer("elementwise_mod")
elementwise_floordiv = _elementwise_layer("elementwise_floordiv")


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None,
           out_dtype=None):
    """out_dtype (TPU extension): accumulate in a wider dtype than the
    inputs (e.g. bf16 operands -> float32 output in one MXU pass) —
    the mixed-precision recipe for vocab-scale projections."""
    helper = LayerHelper("matmul", name=name)
    shape = None
    if x.shape is not None and y.shape is not None:
        xs = list(x.shape)
        ys = list(y.shape)
        if len(xs) >= 2 and len(ys) >= 2:
            m = xs[-1] if transpose_x else xs[-2]
            n = ys[-2] if transpose_y else ys[-1]
            shape = tuple(xs[:-2]) + (m, n) if len(xs) >= len(ys) \
                else tuple(ys[:-2]) + (m, n)
    out = helper.create_variable_for_type_inference(out_dtype or x.dtype,
                                                    shape)
    attrs = {"transpose_X": transpose_x, "transpose_Y": transpose_y,
             "alpha": alpha}
    if out_dtype:
        attrs["out_dtype"] = out_dtype
    helper.append_op("matmul", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mul", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    return _single(helper, "clip", x, {"min": float(min), "max": float(max)},
                   x.shape)


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    return _single(helper, "clip_by_norm", x, {"max_norm": float(max_norm)},
                   x.shape)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = _single(helper, "scale", x,
                  {"scale": float(scale), "bias": float(bias),
                   "bias_after_scale": bias_after_scale}, x.shape)
    return helper.append_activation(out)


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    return _single(helper, "mean", x, shape=(1,))


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------

def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", act=act, name=name)
    known = [s for s in shape if s not in (-1,)]
    out = helper.create_variable_for_type_inference(x.dtype, tuple(shape))
    helper.append_op("reshape2", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"shape": [int(s) for s in shape]})
    return helper.append_activation(out)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    shape = None
    if input.shape is not None:
        shape = tuple(s for i, s in enumerate(input.shape)
                      if not (i in [a % len(input.shape) for a in axes]
                              and s == 1))
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("squeeze2", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    shape = None
    if input.shape is not None:
        shape = list(input.shape)
        for a in sorted(axes):
            shape.insert(a if a >= 0 else a + len(shape) + 1, 1)
        shape = tuple(shape)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("unsqueeze2", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axes": list(axes)})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    shape = tuple(x.shape[p] for p in perm) if x.shape is not None else None
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("transpose2", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": list(perm)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    import math as _m
    shape = None
    if x.shape is not None and all(s != -1 for s in x.shape[axis:]):
        lead = x.shape[:axis]
        shape = ((-1 if any(s == -1 for s in lead)
                  else int(_m.prod(lead or (1,)))),
                 int(_m.prod(x.shape[axis:])))
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("flatten2", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        num, sections = num_or_sections, []
        n_out = num
    else:
        num, sections = 0, list(num_or_sections)
        n_out = len(sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n_out)]
    helper.append_op("split", inputs={"X": [input.name]},
                     outputs={"Out": [o.name for o in outs]},
                     attrs={"num": num, "sections": sections, "axis": dim})
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    shape = None
    if x[0].shape is not None:
        shape = list(x[0].shape)
        shape.insert(axis if axis >= 0 else axis + len(shape) + 1, len(x))
        shape = tuple(shape)
    out = helper.create_variable_for_type_inference(x[0].dtype, shape)
    helper.append_op("stack", inputs={"X": [v.name for v in x]},
                     outputs={"Y": [out.name]}, attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    num = num if num is not None else x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op("unstack", inputs={"X": [x.name]},
                     outputs={"Y": [o.name for o in outs]},
                     attrs={"axis": axis, "num": num})
    return outs


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    shape = None
    if input.shape is not None:
        shape = list(input.shape)
        for a, s, e in zip(axes, starts, ends):
            dim = shape[a]
            if dim == -1:
                continue
            s2 = max(s + dim, 0) if s < 0 else min(s, dim)
            e2 = max(e + dim, 0) if e < 0 else min(e, dim)
            shape[a] = max(e2 - s2, 0)
        shape = tuple(shape)
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("slice", inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    shape = None
    if x.shape is not None:
        shape = tuple(-1 if s == -1 else s * t
                      for s, t in zip(x.shape, expand_times))
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("expand", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"expand_times": list(expand_times)})
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    shape = None
    if input.shape is not None and index.shape is not None:
        m = index.shape[0]
        shape = (m,) + tuple(input.shape[1:])
    out = helper.create_variable_for_type_inference(input.dtype, shape)
    helper.append_op("gather", inputs={"X": [input.name],
                                       "Index": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather_nd", inputs={"X": [input.name],
                                          "Index": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype, input.shape)
    helper.append_op("scatter",
                     inputs={"X": [input.name], "Ids": [index.name],
                             "Updates": [updates.name]},
                     outputs={"Out": [out.name]},
                     attrs={"overwrite": overwrite})
    return out


def scatter_nd_add(ref, index, updates, name=None):
    helper = LayerHelper("scatter_nd_add", name=name)
    out = helper.create_variable_for_type_inference(ref.dtype, ref.shape)
    helper.append_op("scatter_nd_add",
                     inputs={"X": [ref.name], "Index": [index.name],
                             "Updates": [updates.name]},
                     outputs={"Out": [out.name]})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    shape = None
    if x.shape is not None and len(paddings) >= 2 * len(x.shape):
        shape = tuple(
            d if d == -1 else d + paddings[2 * i] + paddings[2 * i + 1]
            for i, d in enumerate(x.shape))
    out = helper.create_variable_for_type_inference(x.dtype, shape)
    helper.append_op("pad", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"paddings": list(paddings),
                            "pad_value": float(pad_value)})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pad2d", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": float(pad_value)})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    helper = LayerHelper("image_resize", name=name)
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    op = "interp_bilinear" if resample.upper() == "BILINEAR" \
        else "interp_nearest"
    out = helper.create_variable_for_type_inference(
        input.dtype, (input.shape[0], input.shape[1]) + tuple(out_shape))
    helper.append_op(op, inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"out_h": int(out_shape[0]),
                            "out_w": int(out_shape[1]),
                            "align_corners": bool(align_corners),
                            "align_mode": int(align_mode)})
    return out


resize_bilinear = image_resize


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        align_corners=align_corners)


# ---------------------------------------------------------------------------
# reductions / softmax / misc
# ---------------------------------------------------------------------------

def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        if dim is None:
            attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
            shape = (1,) if not keep_dim else None
        else:
            dims = [dim] if isinstance(dim, int) else list(dim)
            attrs = {"dim": dims, "keep_dim": keep_dim, "reduce_all": False}
            shape = None
            if input.shape is not None:
                nd = len(input.shape)
                axes = {d % nd for d in dims}
                shape = tuple(
                    (1 if keep_dim else None) if i in axes else s
                    for i, s in enumerate(input.shape))
                shape = tuple(s for s in shape if s is not None)
        out = helper.create_variable_for_type_inference(input.dtype, shape)
        helper.append_op(op_type, inputs={"X": [input.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out
    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")
reduce_all = _reduce_layer("reduce_all")
reduce_any = _reduce_layer("reduce_any")


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    return _single(helper, "softmax", input, {"axis": axis}, input.shape)


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    return _single(helper, "log_softmax", input, {"axis": axis}, input.shape)


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    shape = None
    if input.shape is not None:
        shape = tuple(input.shape[:-1]) + (k,)
    values = helper.create_variable_for_type_inference(input.dtype, shape)
    indices = helper.create_variable_for_type_inference("int64", shape)
    helper.append_op("top_k", inputs={"X": [input.name]},
                     outputs={"Out": [values.name],
                              "Indices": [indices.name]},
                     attrs={"k": k})
    indices.stop_gradient = True
    return values, indices


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"depth": depth, "dtype": "float32"})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    inputs = {"X": [label.name]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist.name]
    out = helper.create_variable_for_type_inference(dtype, label.shape)
    helper.append_op("label_smooth", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"epsilon": float(epsilon)})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    shape = None
    if maxlen is not None and maxlen > 0 and x.shape is not None:
        shape = tuple(x.shape) + (maxlen,)
    out = helper.create_variable_for_type_inference(dtype, shape)
    helper.append_op("sequence_mask", inputs={"X": [x.name]},
                     outputs={"Y": [out.name]},
                     attrs={"maxlen": maxlen if maxlen is not None else -1,
                            "out_dtype": dtype})
    out.stop_gradient = True
    return out


def where(condition, x=None, y=None):
    """Ternary select (modern paddle.where); for index extraction see
    layers.where_index."""
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference(x.dtype, x.shape)
    helper.append_op("where", inputs={"Condition": [condition.name],
                                      "X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(
        "int32", (len(input.shape),) if input.shape else None)
    helper.append_op("shape", inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]})
    out.stop_gradient = True
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    return _single(helper, "cumsum", x,
                   {"axis": axis, "exclusive": exclusive, "reverse": reverse},
                   x.shape)


def cast(x, dtype):
    return tensor_layers.cast(x, dtype)


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    n, c, h, w = x.shape
    r = reshape(x, [-1 if n == -1 else n, c // groups, groups, h, w])
    return reduce_max(r, dim=2)


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [x.shape[1]]
    else:
        shape = [int(s) for s in x.shape[1:]]
    alpha = helper.create_parameter(
        helper.param_attr, shape=shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25))
    pos = _single(LayerHelper("relu"), "relu", x, shape=x.shape)
    neg_in = elementwise_min(x, tensor_layers.zeros([1], x.dtype))
    if mode == "channel":
        neg = elementwise_mul(neg_in, alpha, axis=1)
    else:
        neg = elementwise_mul(neg_in, alpha)
    return elementwise_add(pos, neg)


def embedding_bag(input, size, mode="sum", padding_idx=None,
                  param_attr=None, dtype="float32"):
    """Bagged embedding lookup: ids (N, bag) -> (N, D) reduced over the
    bag axis. Composition of lookup_table + reduction; XLA fuses the
    gather and the reduce into one pass."""
    emb = embedding(input, size, padding_idx=padding_idx,
                    param_attr=param_attr, dtype=dtype)   # (N, bag, D)
    if mode == "sum":
        return reduce_sum(emb, dim=1)
    if mode == "mean":
        return reduce_mean(emb, dim=1)
    if mode == "max":
        return reduce_max(emb, dim=1)
    raise ValueError("embedding_bag mode must be sum/mean/max")


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Global step counter var (reference layers/nn.py) — persistable int64
    incremented once per executor run."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER_LR@"
    counter = helper.create_or_get_global_variable(
        name=name, dtype="int64", shape=(1,), persistable=True)
    if not getattr(counter, "_step_init_done", False):
        helper.set_variable_initializer(
            counter, __import__(
                "paddle_tpu.initializer", fromlist=["ConstantInitializer"]
            ).ConstantInitializer(float(begin - step)))
        counter._step_init_done = True
    out = helper.create_variable_for_type_inference("int64", (1,))
    helper.append_op("increment", inputs={"X": [counter.name]},
                     outputs={"Out": [counter.name]},
                     attrs={"step": float(step), "op_role": "lr_sched"})
    helper.append_op("assign", inputs={"X": [counter.name]},
                     outputs={"Out": [out.name]})
    counter.stop_gradient = True
    out.stop_gradient = True
    return out


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None, name=None):
    """Host-python escape hatch (reference layers/nn.py:12369 py_func /
    operators/py_func_op.cc). TPU-native: the call embeds in the jitted
    step via jax.pure_callback; backward_func (contract:
    backward_func(*inputs, *outputs, *out_grads) -> per-input grads,
    None entries allowed) becomes a custom-vjp callback, so py_func ops
    sit inside a differentiable program.

    ``out`` must be pre-created Variables with static shapes (XLA needs
    the callback's result shapes at trace time), exactly like the
    reference requires create_variable'd outs. The function object lives
    in a process-local registry — programs using py_func serialize
    structurally but need the same process to run (same pickling caveat
    as the reference).
    """
    from ..ops.misc_ops import register_py_func
    if skip_vars_in_backward_input:
        raise NotImplementedError(
            "py_func skip_vars_in_backward_input is not supported — the "
            "backward callback always receives (*inputs, *outputs, "
            "*out_grads); drop the skip list and index accordingly")
    helper = LayerHelper("py_func", name=name)
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        if o.shape is None or any(s in (None, -1) for s in o.shape):
            raise ValueError(
                "py_func outputs need fully static shapes on TPU; got %r "
                "for %s" % (o.shape, o.name))
    fid = register_py_func(func, backward_func)
    helper.append_op(
        "py_func",
        inputs={"X": [v.name for v in xs]},
        outputs={"Out": [o.name for o in outs]},
        attrs={"func_id": fid,
               "out_meta": [[list(o.shape), str(o.dtype)] for o in outs]})
    return out
