"""Remaining reference layers/nn.py public surface.

Reference parity: python/paddle/fluid/layers/nn.py — each function cites
its reference name; kernels live in ops/extras_ops.py where a composition
of existing ops does not suffice. SelectedRows-specific helpers are
identity by design (TPU grads are dense; there is no SelectedRows format).
"""
import math

import numpy as np

from ..layer_helper import LayerHelper
from ..framework.program import default_main_program
from . import tensor as T
from .nn import (reduce_sum, elementwise_mul, elementwise_add,
                 elementwise_sub, elementwise_div, one_hot, reshape,
                 transpose, matmul, scale, cast)

__all__ = [
    "add_position_encoding", "affine_channel", "continuous_value_model",
    "ctc_greedy_decoder", "deformable_roi_pooling", "dice_loss",
    "expand_as", "filter_by_instag", "fsp_matrix", "gather_tree",
    "gaussian_random_batch_size_like", "get_tensor_from_selected_rows",
    "hash", "im2sequence", "image_resize_short", "lod_append", "lod_reset",
    "merge_selected_rows", "pad_constant_like", "random_crop", "rank",
    "resize_trilinear", "scatter_nd", "shard_index", "shuffle_channel",
    "similarity_focus", "size", "space_to_depth", "strided_slice", "sum",
    "uniform_random_batch_size_like",
]


def _append(op_type, inputs, out_dtype, attrs=None, n_out=1,
            out_slots=("Out",), out_dtypes=None, name=None,
            out_shapes=None):
    helper = LayerHelper(op_type, name=name)
    out_dtypes = out_dtypes or [out_dtype] * n_out
    out_shapes = out_shapes or [None] * n_out
    outs = [helper.create_variable_for_type_inference(dt, shape=sh)
            for dt, sh in zip(out_dtypes, out_shapes)]
    helper.append_op(op_type,
                     inputs={k: [v.name for v in vs]
                             for k, vs in inputs.items()},
                     outputs={s: [o.name] for s, o in zip(out_slots, outs)},
                     attrs=attrs or {})
    return outs[0] if n_out == 1 else outs


# ---- simple metadata / elementwise -------------------------------------

def rank(input):
    """Static rank as a (1,) int32 constant (ref nn.py rank)."""
    return T.fill_constant([1], "int32", len(input.shape))


def size(input):
    """Total element count as a (1,) int64 constant (ref nn.py size)."""
    n = 1
    for s in input.shape:
        if s in (None, -1):
            raise ValueError("size() needs fully static shapes on TPU")
        n *= s
    return T.fill_constant([1], "int64", n)


def sum(x):
    """Elementwise sum of a list of tensors (ref nn.py sum op)."""
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    helper = LayerHelper("sum")
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("sum", inputs={"X": [v.name for v in xs]},
                     outputs={"Out": [out.name]})
    return out


def expand_as(x, target_tensor, name=None):
    """Broadcast x to target's shape (ref nn.py expand_as)."""
    return _append("expand_as",
                   {"X": [x], "target_tensor": [target_tensor]},
                   x.dtype, name=name)


def strided_slice(input, axes, starts, ends, strides):
    """ref nn.py strided_slice."""
    return _append("strided_slice", {"Input": [input]}, input.dtype,
                   attrs={"axes": list(axes), "starts": list(starts),
                          "ends": list(ends), "strides": list(strides)})


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Map ids into shard-local ids; ids outside this shard become
    ignore_value (ref nn.py shard_index)."""
    if not 0 <= shard_id < nshards:
        raise ValueError("shard_id %d out of range [0, %d)"
                         % (shard_id, nshards))
    from .control_flow import less_than, logical_and, greater_equal
    from .nn import where
    shard_size = (index_num + nshards - 1) // nshards
    lo = T.fill_constant([1], str(input.dtype), shard_id * shard_size)
    hi = T.fill_constant([1], str(input.dtype),
                         (shard_id + 1) * shard_size)
    in_shard = logical_and(less_than(input, hi),
                           greater_equal(input, lo))
    local = elementwise_sub(input, lo)
    ign = scale(T.ones_like(input), scale=0.0, bias=float(ignore_value))
    return where(in_shard, local, cast(ign, str(input.dtype)))


# ---- losses / feature transforms ---------------------------------------

def dice_loss(input, label, epsilon=1e-5):
    """1 - 2*|X∩Y| / (|X|+|Y|) over one-hot labels (ref nn.py dice_loss:
    input (N, ..., C) probabilities, label (N, ..., 1) int)."""
    depth = int(input.shape[-1])
    lab = one_hot(reshape(label, list(label.shape[:-1])), depth)
    reduce_dims = list(range(1, len(input.shape)))
    inter = reduce_sum(elementwise_mul(input, lab), dim=reduce_dims)
    union = elementwise_add(reduce_sum(input, dim=reduce_dims),
                            reduce_sum(lab, dim=reduce_dims))
    dice = elementwise_div(scale(inter, scale=2.0),
                           scale(union, bias=epsilon))
    from .nn import reduce_mean
    return reduce_mean(scale(dice, scale=-1.0, bias=1.0))


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """out = alpha*x + beta*sinusoidal_PE (ref nn.py
    add_position_encoding); input (N, T, D)."""
    _, t, d = input.shape
    pos = np.arange(t)[:, None]
    div = np.exp(np.arange(0, d, 2) * -(math.log(10000.0) / d))
    pe = np.zeros((t, d), np.float32)
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div[: d // 2])
    pe_var = T.assign(pe.reshape(1, t, d))
    return elementwise_add(scale(input, scale=float(alpha)),
                           scale(pe_var, scale=float(beta)))


def affine_channel(x, scale_var=None, bias=None, data_layout="NCHW",
                   name=None, act=None):
    """Per-channel x*scale + bias (ref nn.py affine_channel)."""
    c_axis = 1 if data_layout == "NCHW" else len(x.shape) - 1
    shape = [1] * len(x.shape)
    shape[c_axis] = x.shape[c_axis]
    out = elementwise_add(
        elementwise_mul(x, reshape(scale_var, shape)),
        reshape(bias, shape))
    if act:
        from . import ops as act_ops
        out = getattr(act_ops, act)(out)
    return out


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix (ref nn.py fsp_matrix):
    (N,C1,H,W),(N,C2,H,W) -> (N,C1,C2) = x_flat y_flat^T / (H*W)."""
    n, c1, h, w = x.shape
    c2 = y.shape[1]
    xf = reshape(x, [n, c1, h * w])
    yf = transpose(reshape(y, [n, c2, h * w]), [0, 2, 1])
    return scale(matmul(xf, yf), scale=1.0 / float(h * w))


def continuous_value_model(input, cvm, use_cvm=True):
    """Show/click CTR embedding handling (ref nn.py
    continuous_value_model)."""
    return _append("cvm", {"X": [input], "CVM": [cvm]}, input.dtype,
                   attrs={"use_cvm": bool(use_cvm)}, out_slots=("Y",))


# ---- shape/layout ops ---------------------------------------------------

def space_to_depth(x, blocksize, name=None):
    b = int(blocksize)
    shape = None
    if x.shape and all(s not in (None, -1) for s in x.shape):
        n, c, h, w = x.shape
        shape = (n, c * b * b, h // b, w // b)
    return _append("space_to_depth", {"X": [x]}, x.dtype,
                   attrs={"blocksize": b}, name=name,
                   out_shapes=[shape])


def shuffle_channel(x, group, name=None):
    return _append("shuffle_channel", {"X": [x]}, x.dtype,
                   attrs={"group": int(group)}, name=name,
                   out_shapes=[tuple(x.shape) if x.shape else None])


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y at the end of every dim up to x's shape (ref nn.py
    pad_constant_like)."""
    from .nn import pad
    paddings = []
    for sx, sy in zip(x.shape, y.shape):
        paddings += [0, int(sx) - int(sy)]
    return pad(y, paddings, pad_value=pad_value)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    """Sliding windows -> rows (ref nn.py im2sequence): (N,C,H,W) ->
    (N*oh*ow, C*fh*fw) via the unfold kernel."""
    from .vision import unfold
    fh, fw = (filter_size, filter_size) if isinstance(filter_size, int) \
        else filter_size
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    cols = unfold(input, [fh, fw], strides=[sh, sw],
                  paddings=[padding] * 4 if isinstance(padding, int)
                  else padding)                  # (N, C*fh*fw, L)
    n, c, h, w = input.shape
    p = [padding] * 4 if isinstance(padding, int) else list(padding)
    oh = (h + p[0] + p[1] - fh) // sh + 1
    ow = (w + p[2] + p[3] - fw) // sw + 1
    l = oh * ow
    ckk = c * fh * fw
    cols = reshape(cols, [n, ckk, l])
    return reshape(transpose(cols, [0, 2, 1]), [n * l, ckk])


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len (ref nn.py
    image_resize_short); static input H,W."""
    from .nn import image_resize
    _, _, h, w = input.shape
    short = min(h, w)
    oh = int(round(h * out_short_len / float(short)))
    ow = int(round(w * out_short_len / float(short)))
    return image_resize(input, out_shape=[oh, ow],
                        resample=resample)


def resize_trilinear(input, out_shape=None, scale_var=None, name=None,
                     actual_shape=None, align_corners=True,
                     align_mode=1, data_format="NCDHW"):
    """3-D linear resize (ref nn.py resize_trilinear)."""
    if out_shape is None:
        raise ValueError("resize_trilinear needs a static out_shape "
                         "[D, H, W] on TPU")
    return _append("resize_trilinear", {"X": [input]}, input.dtype,
                   attrs={"out_shape": [int(s) for s in out_shape]},
                   name=name)


# ---- indexing / decoding -----------------------------------------------

def scatter_nd(index, updates, shape, name=None):
    """Zeros of `shape` with updates scattered/accumulated at index (ref
    nn.py scatter_nd)."""
    return _append("scatter_nd", {"Index": [index], "Updates": [updates]},
                   updates.dtype, attrs={"shape": [int(s) for s in shape]},
                   name=name)


def gather_tree(ids, parents):
    """Beam-search path reconstruction (ref nn.py gather_tree)."""
    return _append("gather_tree", {"Ids": [ids], "Parents": [parents]},
                   ids.dtype)


def hash(input, hash_size, num_hash=1, name=None):
    """Multi-seed bounded integer hash (ref nn.py hash)."""
    return _append("hash", {"X": [input]}, "int64",
                   attrs={"mod_by": int(hash_size),
                          "num_hash": int(num_hash)}, name=name)


def random_crop(x, shape=None, seed=None):
    """Random spatial crop to trailing `shape` (ref nn.py random_crop)."""
    return _append("random_crop", {"X": [x]}, x.dtype,
                   attrs={"shape": [int(s) for s in shape]})


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=-1,
                       name=None):
    """Greedy CTC decode to dense ids + lengths (ref nn.py
    ctc_greedy_decoder; dense (N, T, V) + lengths replaces LoD)."""
    inputs = {"Input": [input]}
    if input_length is not None:
        inputs["Length"] = [input_length]
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int32")
    helper.append_op("ctc_greedy_decoder",
                     inputs={k: [v.name for v in vs]
                             for k, vs in inputs.items()},
                     outputs={"Out": [out.name],
                              "OutLength": [out_len.name]},
                     attrs={"blank": int(blank),
                            "padding_value": int(padding_value)})
    return out, out_len


def similarity_focus(input, axis, indexes, name=None):
    """ref nn.py similarity_focus."""
    return _append("similarity_focus", {"X": [input]}, input.dtype,
                   attrs={"axis": int(axis),
                          "indexes": [int(i) for i in indexes]}, name=name)


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """ref nn.py filter_by_instag (dense/static form: kept rows packed to
    the top, mask in LossWeight, row mapping in IndexMap)."""
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(ins.dtype)
    lw = helper.create_variable_for_type_inference(ins.dtype)
    im = helper.create_variable_for_type_inference("int64")
    helper.append_op("filter_by_instag",
                     inputs={"Ins": [ins.name], "Ins_tag": [ins_tag.name],
                             "Filter_tag": [filter_tag.name]},
                     outputs={"Out": [out.name], "LossWeight": [lw.name],
                              "IndexMap": [im.name]})
    return out, lw, im


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           name=None):
    """Deformable RoI pooling (ref nn.py deformable_roi_pooling):
    implemented as psroi/roi pooling with per-bin offsets from `trans`.
    TPU note: offsets shift the bin sampling grid before bilinear
    sampling; the no_trans path reduces to (ps)roi_pool."""
    from .vision import psroi_pool, prroi_pool
    if no_trans:
        if position_sensitive:
            c = int(input.shape[1]) // (pooled_height * pooled_width)
            return psroi_pool(input, rois, c, spatial_scale,
                              pooled_height, pooled_width)
        return prroi_pool(input, rois, spatial_scale, pooled_height,
                          pooled_width)
    helper = LayerHelper("deformable_roi_pooling", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "deformable_roi_pooling",
        inputs={"Input": [input.name], "ROIs": [rois.name],
                "Trans": [trans.name]},
        outputs={"Output": [out.name]},
        attrs={"spatial_scale": float(spatial_scale),
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "trans_std": float(trans_std),
               "position_sensitive": bool(position_sensitive)})
    return out


# ---- random batch-size-like --------------------------------------------

def _batch_size_like_shape(input, shape, input_dim_idx, output_dim_idx):
    shape = [int(s) for s in shape]
    b = input.shape[input_dim_idx]
    if b in (None, -1):
        raise ValueError("*_batch_size_like needs a static batch dim")
    shape[output_dim_idx] = int(b)
    return shape


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    """ref nn.py gaussian_random_batch_size_like."""
    shape = _batch_size_like_shape(input, shape, input_dim_idx,
                                   output_dim_idx)
    from .ops import gaussian_random
    return gaussian_random(shape, mean=mean, std=std, seed=seed,
                           dtype=dtype)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    """ref nn.py uniform_random_batch_size_like."""
    shape = _batch_size_like_shape(input, shape, input_dim_idx,
                                   output_dim_idx)
    from .ops import uniform_random
    return uniform_random(shape, dtype=dtype, min=min, max=max, seed=seed)


# ---- LoD / SelectedRows parity shims -----------------------------------

def lod_reset(x, y=None, target_lod=None):
    """Dense+lengths design: LoD metadata travels as explicit length
    vectors, so resetting LoD is pairing x with the new lengths (ref
    nn.py lod_reset). Returns x unchanged; pass the new lengths alongside
    to the sequence_* ops."""
    return x


def lod_append(x, level):
    """See lod_reset — LoD is external lengths here (ref lod_append)."""
    return x


def get_tensor_from_selected_rows(x, name=None):
    """Identity: TPU gradients are dense; there is no SelectedRows format
    (ref get_tensor_from_selected_rows)."""
    return x


def merge_selected_rows(x, name=None):
    """Identity — duplicate-row accumulation already happened in the
    dense grad (ref merge_selected_rows)."""
    return x
