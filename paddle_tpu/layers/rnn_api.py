"""fluid 1.6 cell-based RNN API (ref python/paddle/fluid/layers/rnn.py:
RNNCell/GRUCell/LSTMCell, rnn(), lstm(), dynamic_lstmp()).

TPU design: ``rnn(cell, ...)`` records ONE step of the cell inside a
DynamicRNN block and lowers to a single differentiable lax.scan
(recurrent_scan op), with dense+lengths padding semantics: padded steps
freeze the state carry and zero the outputs, so the returned final
states are the states at each row's last valid step.  Cell parameters
are created on first call with names pinned per cell instance, so one
cell can be reused across unrolled decoders.
"""
import numpy as np

from ..layer_helper import LayerHelper
from ..framework import unique_name
from . import nn as _nn
from . import ops as _ops
from . import tensor as _tensor
from .control_flow import DynamicRNN

__all__ = ["RNNCell", "GRUCell", "LSTMCell", "rnn", "lstm",
           "dynamic_lstmp"]


def _flatten(structure):
    if isinstance(structure, (list, tuple)):
        out = []
        for s in structure:
            out.extend(_flatten(s))
        return out
    return [structure]


def _pack_as(structure, flat):
    it = iter(flat)

    def walk(s):
        if isinstance(s, (list, tuple)):
            return type(s)(walk(x) for x in s)
        return next(it)

    return walk(structure)


class RNNCell(object):
    """Base cell (ref rnn.py:48): ``call(inputs, states) -> (outputs,
    new_states)``; ``get_initial_states`` builds zero states shaped per
    ``state_shape`` with the batch dim taken from ``batch_ref``."""

    def call(self, inputs, states, **kwargs):
        raise NotImplementedError("RNNCell must implement call().")

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)

    @property
    def state_shape(self):
        raise NotImplementedError(
            "cell has no state_shape; pass shape= to get_initial_states")

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0):
        batch_ref = _flatten(batch_ref)[0]
        shapes = self.state_shape if shape is None else shape
        dtype = dtype or "float32"
        nested = shapes if isinstance(shapes[0], (list, tuple)) \
            else [shapes]
        outs = []
        for s in nested:
            full = list(s) if s and s[0] == -1 else [-1] + list(s)
            outs.append(_tensor.fill_constant_batch_size_like(
                batch_ref, shape=full, dtype=dtype, value=init_value))
        return outs[0] if len(outs) == 1 else outs


class GRUCell(RNNCell):
    """Single-step GRU (ref rnn.py GRUCell): state = hidden (B, H);
    outputs = new hidden."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, dtype="float32",
                 name="gru_cell"):
        self.hidden_size = hidden_size
        self._uid = unique_name.generate(name)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_act = gate_activation or "sigmoid"
        self._act = activation or "tanh"
        self._dtype = dtype

    def _attr(self, suffix, base):
        """Pin a per-cell name; honor a user initializer if given."""
        from ..param_attr import ParamAttr
        attr = ParamAttr(name=self._uid + suffix)
        if base is not None and getattr(base, "initializer", None):
            attr.initializer = base.initializer
        return attr

    @property
    def state_shape(self):
        return [self.hidden_size]

    def call(self, inputs, states):
        h = self.hidden_size
        gates = _nn.fc(
            _tensor.concat([inputs, states], axis=-1), size=2 * h,
            act=self._gate_act,
            param_attr=self._attr("_gate_w", self._param_attr),
            bias_attr=self._attr("_gate_b", self._bias_attr))
        u = _nn.slice(gates, axes=[1], starts=[0], ends=[h])
        r = _nn.slice(gates, axes=[1], starts=[h], ends=[2 * h])
        cand = _nn.fc(
            _tensor.concat([inputs, _nn.elementwise_mul(r, states)],
                           axis=-1),
            size=h, act=self._act,
            param_attr=self._attr("_cand_w", self._param_attr),
            bias_attr=self._attr("_cand_b", self._bias_attr))
        ones = _nn.scale(u, scale=-1.0, bias=1.0)
        new_h = _nn.elementwise_add(_nn.elementwise_mul(u, states),
                                    _nn.elementwise_mul(ones, cand))
        return new_h, new_h


class LSTMCell(RNNCell):
    """Single-step LSTM (ref rnn.py LSTMCell): states = [h, c];
    outputs = new h."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32", name="lstm_cell"):
        self.hidden_size = hidden_size
        self._uid = unique_name.generate(name)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_act = gate_activation or "sigmoid"
        self._act = activation or "tanh"
        self._forget_bias = forget_bias
        self._dtype = dtype

    _attr = GRUCell._attr

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]

    def call(self, inputs, states):
        pre_h, pre_c = states
        h = self.hidden_size
        gact = getattr(_ops, self._gate_act)
        act = getattr(_ops, self._act)
        gates = _nn.fc(
            _tensor.concat([inputs, pre_h], axis=-1), size=4 * h,
            param_attr=self._attr("_w", self._param_attr),
            bias_attr=self._attr("_b", self._bias_attr))
        i = gact(_nn.slice(gates, axes=[1], starts=[0], ends=[h]))
        f = gact(_nn.scale(
            _nn.slice(gates, axes=[1], starts=[h], ends=[2 * h]),
            bias=self._forget_bias))
        c_t = act(_nn.slice(gates, axes=[1], starts=[2 * h],
                            ends=[3 * h]))
        o = gact(_nn.slice(gates, axes=[1], starts=[3 * h],
                           ends=[4 * h]))
        new_c = _nn.elementwise_add(_nn.elementwise_mul(f, pre_c),
                                    _nn.elementwise_mul(i, c_t))
        new_h = _nn.elementwise_mul(o, act(new_c))
        return new_h, [new_h, new_c]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Scan ``cell`` over time (ref rnn.py:363) -> (outputs,
    final_states).  One lax.scan; padded steps (per sequence_length)
    freeze the state and zero the outputs."""
    from .sequence_lod import sequence_reverse
    if time_major:
        inputs = _nn.transpose(inputs, perm=[1, 0, 2])
    if initial_states is None:
        initial_states = cell.get_initial_states(inputs)
    flat_init = _flatten(initial_states)
    length_aware_reverse = is_reverse and sequence_length is not None
    if length_aware_reverse:
        inputs = sequence_reverse(inputs, lengths=sequence_length)
    elif is_reverse:
        from .tensor import reverse
        inputs = reverse(inputs, axis=[1])

    drnn = DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(inputs, lengths=sequence_length)
        mems = [drnn.memory(init=s) for s in flat_init]
        out, new_states = cell(x_t, _pack_as(initial_states, mems),
                               **kwargs)
        flat_new = _flatten(new_states)
        for m, ns in zip(mems, flat_new):
            drnn.update_memory(m, ns)
        outs = out if isinstance(out, (list, tuple)) else [out]
        drnn.output(*outs)
    outputs = drnn()
    final_states = _pack_as(initial_states, drnn.final_states())
    seq_outs = outputs if isinstance(outputs, list) else [outputs]
    if length_aware_reverse:
        seq_outs = [sequence_reverse(o, lengths=sequence_length)
                    for o in seq_outs]
    elif is_reverse:
        from .tensor import reverse
        seq_outs = [reverse(o, axis=[1]) for o in seq_outs]
    if time_major:
        seq_outs = [_nn.transpose(o, perm=[1, 0, 2]) for o in seq_outs]
    final_outputs = seq_outs[0] if not isinstance(out, (list, tuple)) \
        else type(out)(seq_outs)
    return final_outputs, final_states


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer (bi)LSTM (ref rnn.py:1337, the cuDNN-LSTM wrapper):
    input (B, T, D); init_h/init_c (num_layers*dirs, B, H).  Built on
    contrib basic_lstm — one scan per layer/direction on TPU instead of
    a monolithic cuDNN call.  Returns (rnn_out, last_h, last_c)."""
    from ..contrib.layers import basic_lstm
    out, last_h, last_c = basic_lstm(
        input, init_h, init_c, hidden_size, num_layers=num_layers,
        dropout_prob=0.0 if is_test else dropout_prob,
        bidirectional=is_bidirec, batch_first=True, dtype=input.dtype)
    return out, last_h, last_c


def dynamic_lstmp(input, size, proj_size, param_attr=None,
                  bias_attr=None, use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None):
    if use_peepholes:
        raise NotImplementedError(
            "dynamic_lstmp use_peepholes is not implemented in "
            "paddle_tpu; pass use_peepholes=False")
    """LSTM with recurrent projection (ref rnn.py:1512 / dynamic_lstmp
    op): input (B, T, 4*H) pre-projected like dynamic_lstm; the hidden
    state is projected to ``proj_size`` before recurrence.  Returns
    (projection (B, T, P), cell (B, T, H))."""
    from ..param_attr import ParamAttr
    hidden = size // 4
    uid = unique_name.generate(name or "lstmp")

    class _LSTMPCell(RNNCell):
        @property
        def state_shape(self):
            return [[proj_size], [hidden]]

        def call(self, x_t, states):
            pre_p, pre_c = states
            gates = _nn.elementwise_add(
                x_t, _nn.fc(pre_p, size=4 * hidden, bias_attr=False,
                            param_attr=ParamAttr(name=uid + "_rw")))
            gact = getattr(_ops, gate_activation)
            cact = getattr(_ops, candidate_activation)
            i = gact(_nn.slice(gates, axes=[1], starts=[0],
                               ends=[hidden]))
            f = gact(_nn.slice(gates, axes=[1], starts=[hidden],
                               ends=[2 * hidden]))
            c_t = cact(_nn.slice(gates, axes=[1],
                                 starts=[2 * hidden],
                                 ends=[3 * hidden]))
            o = gact(_nn.slice(gates, axes=[1],
                               starts=[3 * hidden],
                               ends=[4 * hidden]))
            new_c = _nn.elementwise_add(
                _nn.elementwise_mul(f, pre_c),
                _nn.elementwise_mul(i, c_t))
            new_h = _nn.elementwise_mul(o, _ops.tanh(new_c))
            proj = _nn.fc(new_h, size=proj_size, bias_attr=False,
                          act=None if proj_activation == "identity"
                          else proj_activation,
                          param_attr=ParamAttr(name=uid + "_pw"))
            return [proj, new_c], [proj, new_c]

    outs, _finals = rnn(_LSTMPCell(), input, is_reverse=is_reverse)
    return outs[0], outs[1]