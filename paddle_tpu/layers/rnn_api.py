"""fluid 1.6 cell-based RNN API (ref python/paddle/fluid/layers/rnn.py:
RNNCell/GRUCell/LSTMCell, rnn(), lstm(), dynamic_lstmp()).

TPU design: ``rnn(cell, ...)`` records ONE step of the cell inside a
DynamicRNN block and lowers to a single differentiable lax.scan
(recurrent_scan op), with dense+lengths padding semantics: padded steps
freeze the state carry and zero the outputs, so the returned final
states are the states at each row's last valid step.  Cell parameters
are created on first call with names pinned per cell instance, so one
cell can be reused across unrolled decoders.
"""
import numpy as np

from ..layer_helper import LayerHelper
from ..framework import unique_name
from . import nn as _nn
from . import ops as _ops
from . import tensor as _tensor
from .control_flow import DynamicRNN

__all__ = ["RNNCell", "GRUCell", "LSTMCell", "rnn", "lstm",
           "dynamic_lstmp", "Decoder", "BeamSearchDecoder",
           "dynamic_decode", "beam_search", "beam_search_decode"]


def _flatten(structure):
    if isinstance(structure, (list, tuple)):
        out = []
        for s in structure:
            out.extend(_flatten(s))
        return out
    return [structure]


def _pack_as(structure, flat):
    it = iter(flat)

    def walk(s):
        if isinstance(s, (list, tuple)):
            return type(s)(walk(x) for x in s)
        return next(it)

    return walk(structure)


class RNNCell(object):
    """Base cell (ref rnn.py:48): ``call(inputs, states) -> (outputs,
    new_states)``; ``get_initial_states`` builds zero states shaped per
    ``state_shape`` with the batch dim taken from ``batch_ref``."""

    def call(self, inputs, states, **kwargs):
        raise NotImplementedError("RNNCell must implement call().")

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)

    @property
    def state_shape(self):
        raise NotImplementedError(
            "cell has no state_shape; pass shape= to get_initial_states")

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0):
        batch_ref = _flatten(batch_ref)[0]
        shapes = self.state_shape if shape is None else shape
        dtype = dtype or "float32"
        nested = shapes if isinstance(shapes[0], (list, tuple)) \
            else [shapes]
        outs = []
        for s in nested:
            full = list(s) if s and s[0] == -1 else [-1] + list(s)
            outs.append(_tensor.fill_constant_batch_size_like(
                batch_ref, shape=full, dtype=dtype, value=init_value))
        return outs[0] if len(outs) == 1 else outs


class GRUCell(RNNCell):
    """Single-step GRU (ref rnn.py GRUCell): state = hidden (B, H);
    outputs = new hidden."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None, dtype="float32",
                 name="gru_cell"):
        self.hidden_size = hidden_size
        self._uid = unique_name.generate(name)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_act = gate_activation or "sigmoid"
        self._act = activation or "tanh"
        self._dtype = dtype

    def _attr(self, suffix, base):
        """Pin a per-cell name; honor a user initializer if given."""
        from ..param_attr import ParamAttr
        attr = ParamAttr(name=self._uid + suffix)
        if base is not None and getattr(base, "initializer", None):
            attr.initializer = base.initializer
        return attr

    @property
    def state_shape(self):
        return [self.hidden_size]

    def call(self, inputs, states):
        h = self.hidden_size
        gates = _nn.fc(
            _tensor.concat([inputs, states], axis=-1), size=2 * h,
            act=self._gate_act,
            param_attr=self._attr("_gate_w", self._param_attr),
            bias_attr=self._attr("_gate_b", self._bias_attr))
        u = _nn.slice(gates, axes=[1], starts=[0], ends=[h])
        r = _nn.slice(gates, axes=[1], starts=[h], ends=[2 * h])
        cand = _nn.fc(
            _tensor.concat([inputs, _nn.elementwise_mul(r, states)],
                           axis=-1),
            size=h, act=self._act,
            param_attr=self._attr("_cand_w", self._param_attr),
            bias_attr=self._attr("_cand_b", self._bias_attr))
        ones = _nn.scale(u, scale=-1.0, bias=1.0)
        new_h = _nn.elementwise_add(_nn.elementwise_mul(u, states),
                                    _nn.elementwise_mul(ones, cand))
        return new_h, new_h


class LSTMCell(RNNCell):
    """Single-step LSTM (ref rnn.py LSTMCell): states = [h, c];
    outputs = new h."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32", name="lstm_cell"):
        self.hidden_size = hidden_size
        self._uid = unique_name.generate(name)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_act = gate_activation or "sigmoid"
        self._act = activation or "tanh"
        self._forget_bias = forget_bias
        self._dtype = dtype

    _attr = GRUCell._attr

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]

    def call(self, inputs, states):
        pre_h, pre_c = states
        h = self.hidden_size
        gact = getattr(_ops, self._gate_act)
        act = getattr(_ops, self._act)
        gates = _nn.fc(
            _tensor.concat([inputs, pre_h], axis=-1), size=4 * h,
            param_attr=self._attr("_w", self._param_attr),
            bias_attr=self._attr("_b", self._bias_attr))
        i = gact(_nn.slice(gates, axes=[1], starts=[0], ends=[h]))
        f = gact(_nn.scale(
            _nn.slice(gates, axes=[1], starts=[h], ends=[2 * h]),
            bias=self._forget_bias))
        c_t = act(_nn.slice(gates, axes=[1], starts=[2 * h],
                            ends=[3 * h]))
        o = gact(_nn.slice(gates, axes=[1], starts=[3 * h],
                           ends=[4 * h]))
        new_c = _nn.elementwise_add(_nn.elementwise_mul(f, pre_c),
                                    _nn.elementwise_mul(i, c_t))
        new_h = _nn.elementwise_mul(o, act(new_c))
        return new_h, [new_h, new_c]


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Scan ``cell`` over time (ref rnn.py:363) -> (outputs,
    final_states).  One lax.scan; padded steps (per sequence_length)
    freeze the state and zero the outputs."""
    from .sequence_lod import sequence_reverse
    if time_major:
        inputs = _nn.transpose(inputs, perm=[1, 0, 2])
    if initial_states is None:
        initial_states = cell.get_initial_states(inputs)
    flat_init = _flatten(initial_states)
    length_aware_reverse = is_reverse and sequence_length is not None
    if length_aware_reverse:
        inputs = sequence_reverse(inputs, lengths=sequence_length)
    elif is_reverse:
        from .tensor import reverse
        inputs = reverse(inputs, axis=[1])

    drnn = DynamicRNN()
    with drnn.block():
        x_t = drnn.step_input(inputs, lengths=sequence_length)
        mems = [drnn.memory(init=s) for s in flat_init]
        out, new_states = cell(x_t, _pack_as(initial_states, mems),
                               **kwargs)
        flat_new = _flatten(new_states)
        for m, ns in zip(mems, flat_new):
            drnn.update_memory(m, ns)
        outs = out if isinstance(out, (list, tuple)) else [out]
        drnn.output(*outs)
    outputs = drnn()
    final_states = _pack_as(initial_states, drnn.final_states())
    seq_outs = outputs if isinstance(outputs, list) else [outputs]
    if length_aware_reverse:
        seq_outs = [sequence_reverse(o, lengths=sequence_length)
                    for o in seq_outs]
    elif is_reverse:
        from .tensor import reverse
        seq_outs = [reverse(o, axis=[1]) for o in seq_outs]
    if time_major:
        seq_outs = [_nn.transpose(o, perm=[1, 0, 2]) for o in seq_outs]
    final_outputs = seq_outs[0] if not isinstance(out, (list, tuple)) \
        else type(out)(seq_outs)
    return final_outputs, final_states


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer (bi)LSTM (ref rnn.py:1337, the cuDNN-LSTM wrapper):
    input (B, T, D); init_h/init_c (num_layers*dirs, B, H).  Built on
    contrib basic_lstm — one scan per layer/direction on TPU instead of
    a monolithic cuDNN call.  ``seed`` is ignored (dropout masks come
    from the framework's deterministic per-op PRNG).  Returns
    (rnn_out, last_h, last_c)."""
    if default_initializer is not None:
        raise NotImplementedError(
            "lstm(default_initializer=...) is not supported; set "
            "initializers via ParamAttr on a cell-based rnn() instead")
    from ..contrib.layers import basic_lstm
    out, last_h, last_c = basic_lstm(
        input, init_h, init_c, hidden_size, num_layers=num_layers,
        dropout_prob=0.0 if is_test else dropout_prob,
        bidirectional=is_bidirec, batch_first=True, dtype=input.dtype)
    return out, last_h, last_c


def dynamic_lstmp(input, size, proj_size, param_attr=None,
                  bias_attr=None, use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None):
    """LSTM with recurrent projection (ref rnn.py:1512 / dynamic_lstmp
    op): input (B, T, 4*H) pre-projected like dynamic_lstm; the hidden
    state is projected to ``proj_size`` before recurrence.  Returns
    (projection (B, T, P), cell (B, T, H))."""
    if use_peepholes:
        raise NotImplementedError(
            "dynamic_lstmp use_peepholes is not implemented in "
            "paddle_tpu; pass use_peepholes=False")
    from ..param_attr import ParamAttr
    hidden = size // 4
    uid = unique_name.generate(name or "lstmp")

    class _LSTMPCell(RNNCell):
        @property
        def state_shape(self):
            return [[proj_size], [hidden]]

        def call(self, x_t, states):
            pre_p, pre_c = states
            gates = _nn.elementwise_add(
                x_t, _nn.fc(pre_p, size=4 * hidden, bias_attr=False,
                            param_attr=ParamAttr(name=uid + "_rw")))
            gact = getattr(_ops, gate_activation)
            cact = getattr(_ops, candidate_activation)
            i = gact(_nn.slice(gates, axes=[1], starts=[0],
                               ends=[hidden]))
            f = gact(_nn.slice(gates, axes=[1], starts=[hidden],
                               ends=[2 * hidden]))
            c_t = cact(_nn.slice(gates, axes=[1],
                                 starts=[2 * hidden],
                                 ends=[3 * hidden]))
            o = gact(_nn.slice(gates, axes=[1],
                               starts=[3 * hidden],
                               ends=[4 * hidden]))
            new_c = _nn.elementwise_add(
                _nn.elementwise_mul(f, pre_c),
                _nn.elementwise_mul(i, c_t))
            new_h = _nn.elementwise_mul(o, _ops.tanh(new_c))
            proj = _nn.fc(new_h, size=proj_size, bias_attr=False,
                          act=None if proj_activation == "identity"
                          else proj_activation,
                          param_attr=ParamAttr(name=uid + "_pw"))
            return [proj, new_c], [proj, new_c]

    outs, _finals = rnn(_LSTMPCell(), input, is_reverse=is_reverse)
    return outs[0], outs[1]

# ---------------------------------------------------------------------------
# Decoder protocol + beam search (ref rnn.py:492 Decoder, :588
# BeamSearchDecoder, :1040 dynamic_decode).  dynamic_decode unrolls
# max_step_num steps at trace time over a dense (batch*beam) axis — the
# same design as contrib.decoder, with the tf-style cell/step protocol.
# ---------------------------------------------------------------------------
import collections


def _gather_rows(x, idx, group, stride=None):
    """Grouped gather: the i-th selection (of ``group`` per batch row)
    picks element idx[i] within that row's block of ``stride`` rows of
    ``x`` (stride defaults to group — the square beam-gather case)."""
    stride = group if stride is None else stride
    flat_sel = _nn.reshape(idx, [-1])
    ones = _tensor.fill_constant_batch_size_like(
        flat_sel, [-1], "int64", 1)
    pos = _nn.cumsum(ones, axis=0, exclusive=True)
    g_const = _tensor.fill_constant([1], "int64", group)
    s_const = _tensor.fill_constant([1], "int64", stride)
    row = _nn.elementwise_mul(
        _nn.elementwise_floordiv(pos, g_const), s_const)
    return _nn.gather(x, _nn.elementwise_add(flat_sel, row))


class Decoder(object):
    """Step-decoder protocol (ref rnn.py:492)."""

    def initialize(self, inits):
        """-> (initial_inputs, initial_states, initial_finished)."""
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        """-> (outputs, next_states, next_inputs, next_finished)."""
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        """-> (final_outputs, final_states); default passthrough."""
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """Beam-search decoder over an RNNCell (ref rnn.py:588).

    Dense contract: states/ids carry a flattened batch*beam leading dim;
    ``embedding_fn`` maps (batch*beam,) int64 ids -> cell inputs and
    ``output_fn`` maps cell outputs -> vocab logits.
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished",
                         "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        if embedding_fn is None:
            raise ValueError(
                "BeamSearchDecoder needs embedding_fn: a callable "
                "mapping (batch*beam, 1) int64 ids to cell inputs")
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self._neg_inf = -1e9

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """(B, ...) -> (B*beam, ...) repeating rows (ref :663)."""
        shape = list(x.shape)
        expanded = _nn.expand(_nn.unsqueeze(x, axes=[1]),
                              [1, beam_size] + [1] * (len(shape) - 1))
        return _nn.reshape(expanded, [-1] + shape[1:])

    def initialize(self, initial_cell_states):
        b = self.beam_size
        flat = _flatten(initial_cell_states)
        tiled = [self.tile_beam_merge_with_batch(s, b) for s in flat]
        cell_states = _pack_as(initial_cell_states, tiled)
        ref = flat[0]
        ids = _tensor.fill_constant_batch_size_like(
            ref, shape=[-1, b], dtype="int64", value=self.start_token)
        first = _tensor.fill_constant_batch_size_like(
            ref, shape=[-1, 1], dtype="float32", value=0.0)
        log_probs = first
        if b > 1:
            dead = _tensor.fill_constant_batch_size_like(
                ref, shape=[-1, b - 1], dtype="float32",
                value=self._neg_inf)
            log_probs = _tensor.concat([first, dead], axis=1)
        finished = _tensor.fill_constant_batch_size_like(
            ref, shape=[-1, b], dtype="float32", value=0.0)
        lengths = _tensor.fill_constant_batch_size_like(
            ref, shape=[-1, b], dtype="int64", value=0)
        inputs = self.embedding_fn(_nn.reshape(ids, [-1, 1]))
        state = self.StateWrapper(cell_states, log_probs, finished,
                                  lengths)
        return inputs, state, finished

    def _gather_flat(self, x, beam_idx):
        """Gather along winning beams: x (B*beam, ...), beam_idx (B, beam)
        int64 -> gathered (B*beam, ...)."""
        return _gather_rows(x, beam_idx, self.beam_size)

    def _beam_search_step(self, time, logits, next_cell_states, state):
        b = self.beam_size
        v = logits.shape[-1]
        logp = _nn.log_softmax(logits) if hasattr(_nn, "log_softmax") \
            else _ops.log(_nn.softmax(logits))
        logp = _nn.reshape(logp, [-1, b, v])
        # finished beams may only emit end_token at zero added cost
        end_const = _tensor.fill_constant([1], "int64", self.end_token)
        end_onehot = _nn.reshape(
            _nn.one_hot(_nn.reshape(end_const, [1, 1]), v), [1, 1, v])
        end_row = _nn.scale(_nn.scale(end_onehot, scale=-1.0, bias=1.0),
                            scale=self._neg_inf)
        fin3 = _nn.unsqueeze(state.finished, [2])
        live3 = _nn.scale(fin3, scale=-1.0, bias=1.0)
        logp = _nn.elementwise_add(
            _nn.elementwise_mul(logp, live3),
            _nn.elementwise_mul(end_row, fin3))
        total = _nn.elementwise_add(
            logp, _nn.unsqueeze(state.log_probs, [2]))
        scores, top = _nn.topk(_nn.reshape(total, [-1, b * v]), k=b)
        v_const = _tensor.fill_constant([1], "int64", v)
        parent = _nn.elementwise_floordiv(top, v_const)    # (B, b)
        ids = _nn.elementwise_mod(top, v_const)
        # gather state along winning beams
        flat_new = [self._gather_flat(s, parent)
                    for s in _flatten(next_cell_states)]
        cell_states = _pack_as(next_cell_states, flat_new)
        prev_fin = _nn.reshape(
            self._gather_flat(_nn.reshape(state.finished, [-1, 1]),
                              parent), [-1, b])
        prev_len = _nn.reshape(
            self._gather_flat(_nn.reshape(state.lengths, [-1, 1]),
                              parent), [-1, b])
        now_end = _tensor.cast(
            _compare_eq(ids, end_const), "float32")
        finished = _nn.elementwise_max(prev_fin, now_end)
        live = _nn.scale(prev_fin, scale=-1.0, bias=1.0)
        lengths = _nn.elementwise_add(
            prev_len, _tensor.cast(live, "int64"))
        out = self.OutputWrapper(scores, ids, parent)
        new_state = self.StateWrapper(cell_states, scores, finished,
                                      lengths)
        return out, new_state

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_cell = self.cell(inputs, states.cell_states,
                                        **kwargs)
        logits = self.output_fn(cell_out) if self.output_fn is not None \
            else cell_out
        out, new_state = self._beam_search_step(time, logits, next_cell,
                                                states)
        next_inputs = self.embedding_fn(
            _nn.reshape(out.predicted_ids, [-1, 1]))
        return out, new_state, next_inputs, new_state.finished

    def finalize(self, outputs, final_states, sequence_lengths):
        """Back-trace parent_ids into coherent sequences: returns
        (predicted_ids (B, beam, T), final_states)."""
        seqs, _ = beam_search_decode(
            outputs.predicted_ids, outputs.parent_ids,
            beam_size=self.beam_size, end_id=self.end_token)
        return seqs, final_states


def _compare_eq(x, y):
    from .control_flow import equal
    return equal(x, y)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, **kwargs):
    """Run ``decoder`` until max_step_num (ref rnn.py:1040).  The loop
    is UNROLLED at trace time (fixed trip count — the XLA way); early
    finish is handled by the decoder's finished-masking, so results
    match the reference's dynamic while loop.  Returns (final_outputs,
    final_states)."""
    if max_step_num is None:
        max_step_num = 64
    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    for t in range(int(max_step_num)):
        out, states, inputs, finished = decoder.step(t, inputs, states,
                                                     **kwargs)
        step_outputs.append(out)
    if step_outputs and hasattr(step_outputs[0], "_fields"):
        cols = type(step_outputs[0])(
            *[[getattr(o, f) for o in step_outputs]
              for f in step_outputs[0]._fields])
    else:
        cols = step_outputs
    final_outputs, final_states = decoder.finalize(
        cols, states, getattr(states, "lengths", None))
    if output_time_major and hasattr(final_outputs, "shape") and \
            final_outputs.shape is not None and \
            len(final_outputs.shape) == 3:
        # (B, beam, T) -> (T, B, beam)
        final_outputs = _nn.transpose(final_outputs, perm=[2, 0, 1])
    return final_outputs, final_states


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    """One beam expansion step (ref nn.py beam_search /
    operators/beam_search_op).  Dense contract (no LoD): ``scores``
    (batch*beam, K) candidate scores (accumulated when
    ``is_accumulated``, else per-step log-probs added to ``pre_scores``),
    ``ids`` (batch*beam, K) their token ids, ``pre_ids`` (batch*beam, 1)
    previous tokens (frozen rows, i.e. pre_id == end_id, only re-emit
    end_id at no cost).  Returns (selected_ids (batch*beam, 1),
    selected_scores (batch*beam, 1)[, parent_idx (batch*beam,)]),
    best-first within each batch row.
    """
    b = int(beam_size)
    k = scores.shape[-1]
    if not is_accumulated:
        scores = _nn.elementwise_add(scores, pre_scores)
    end_const = _tensor.fill_constant([1], "int64", end_id)
    fin = _tensor.cast(_compare_eq(_nn.reshape(pre_ids, [-1, 1]),
                                   end_const), "float32")   # (B*b, 1)
    is_end = _tensor.cast(_compare_eq(ids, end_const), "float32")
    # frozen rows: only the end_id candidate stays viable, at pre_score
    keep = _nn.elementwise_mul(is_end, fin)
    alive = _nn.scale(fin, scale=-1.0, bias=1.0)
    neg = _tensor.fill_constant([1], "float32", -1e9)
    scores = _nn.elementwise_add(
        _nn.elementwise_mul(scores, alive),
        _nn.elementwise_add(
            _nn.elementwise_mul(_nn.expand(pre_scores, [1, k]), keep),
            _nn.elementwise_mul(
                _nn.scale(_nn.elementwise_max(keep, alive), scale=-1.0,
                          bias=1.0), _nn.expand(
                    _nn.reshape(neg, [1, 1]), [1, k]))))
    flat_scores = _nn.reshape(scores, [-1, b * k])       # (B, b*K)
    flat_ids = _nn.reshape(ids, [-1, b * k])
    sel_scores, top = _nn.topk(flat_scores, k=b)          # (B, b)
    k_const = _tensor.fill_constant([1], "int64", k)
    parent = _nn.elementwise_floordiv(top, k_const)       # beam index
    # gather the chosen token ids out of the candidate table: top
    # indexes within each batch row's b*K candidates
    sel_ids = _nn.reshape(
        _gather_rows(_nn.reshape(flat_ids, [-1]),
                     _nn.reshape(top, [-1]), group=b, stride=b * k),
        [-1, 1])
    sel_scores = _nn.reshape(sel_scores, [-1, 1])
    if return_parent_idx:
        return sel_ids, sel_scores, _nn.reshape(parent, [-1])
    return sel_ids, sel_scores


def beam_search_decode(ids, parent_ids, beam_size, end_id, scores=None,
                       name=None):
    """Back-trace per-step beam selections into whole sequences
    (ref nn.py beam_search_decode / beam_search_decode_op).  Dense
    contract (no LoD): ``ids`` is a list of T (batch*beam, 1)
    selected-id tensors and ``parent_ids`` a list of T (batch*beam,)
    parent indices, both from ``beam_search(...,
    return_parent_idx=True)`` (parent_ids[0] may be None).  Returns
    (sentence_ids (batch, beam, T), sentence_scores (batch, beam) —
    the last step's selected scores when ``scores`` is given, else
    None).
    """
    b = int(beam_size)
    hist = None
    for t, step_ids in enumerate(ids):
        new_ids = _nn.reshape(step_ids, [-1, 1])
        if hist is None:
            hist = new_ids
        else:
            hist = _tensor.concat(
                [_gather_rows(hist, parent_ids[t], b), new_ids], axis=1)
    T = len(ids)
    sent_scores = None if not scores else _nn.reshape(scores[-1], [-1, b])
    return _nn.reshape(hist, [-1, b, T]), sent_scores
