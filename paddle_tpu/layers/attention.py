"""Attention layers.

Reference parity: fluid nets.scaled_dot_product_attention + the transformer
in PaddlePaddle/models. TPU-native: single fused attention op (XLA or Pallas
flash kernel), plus multi_head_attention with optional tensor-parallel
sharding of the head dimension and sequence-parallel ring attention.
"""
from ..layer_helper import LayerHelper
from .nn import fc, matmul, softmax, dropout, reshape, transpose
from .tensor import concat
from ..param_attr import ParamAttr


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0, is_test=False):
    """queries/keys/values: (N, T, D). Multi-head fused attention."""
    helper = LayerHelper("sdpa")
    n, tq, d = queries.shape
    dh = d // num_heads
    q = transpose(reshape(queries, [0, -1 if tq == -1 else tq, num_heads,
                                    dh]), [0, 2, 1, 3])
    k = transpose(reshape(keys, [0, -1 if keys.shape[1] == -1
                                 else keys.shape[1], num_heads, dh]),
                  [0, 2, 1, 3])
    v = transpose(reshape(values, [0, -1 if values.shape[1] == -1
                                   else values.shape[1], num_heads, dh]),
                  [0, 2, 1, 3])
    out = fused_attention(q, k, v)
    out = reshape(transpose(out, [0, 2, 1, 3]), [0, -1 if tq == -1 else tq,
                                                 d])
    if dropout_rate:
        out = dropout(out, dropout_rate, is_test=is_test)
    return out


def fused_attention(q, k, v, mask=None, scale=None, causal=False,
                    impl="auto", sp_axis="sp", name=None):
    """q,k,v: (B, H, T, Dh) — one fused op; Pallas flash path when available.
    Reference composes this from matmul+softmax+matmul ops.

    impl: "auto" | "xla" | "flash" | "ring" | "ulysses" — the last two
    run sequence-parallel attention over the installed mesh's `sp_axis`:
    ring rotates K/V blocks via ppermute and accepts additive
    key-padding masks (..., 1, T) riding the ring; ulysses re-shards
    heads via all_to_all and accepts any additive mask."""
    helper = LayerHelper("fused_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype, q.shape)
    inputs = {"Q": [q.name], "K": [k.name], "V": [v.name]}
    if mask is not None:
        inputs["Mask"] = [mask.name]
    helper.append_op("scaled_dot_product_attention", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"scale": scale, "causal": causal, "impl": impl,
                            "sp_axis": sp_axis})
    return out


def mha_kv_projection(keys, values, d_key, d_value, n_head,
                      param_initializer=None, name="multi_head_att"):
    """Project encoder output once into head-split K/V for cross-attention
    caching (reference: fast_decoder's static_k/static_v). Uses the same
    parameter names as multi_head_attention's k/v projections, so a decoder
    built for training reuses the identical weights at decode time.
    Returns (static_k, static_v), each (N, H, T_src, Dh)."""
    def _attr(suffix):
        return ParamAttr(name=None if name is None else name + suffix,
                         initializer=param_initializer)

    k = fc(keys, d_key * n_head, num_flatten_dims=2,
           param_attr=_attr("_key_fc.w_0"), bias_attr=_attr("_key_fc.b_0"))
    v = fc(values, d_value * n_head, num_flatten_dims=2,
           param_attr=_attr("_value_fc.w_0"), bias_attr=_attr("_value_fc.b_0"))

    def _split_heads(x, dh):
        r = reshape(x, [0, -1 if x.shape[1] == -1 else x.shape[1],
                        n_head, dh])
        return transpose(r, [0, 2, 1, 3])

    return _split_heads(k, d_key), _split_heads(v, d_value)


def multi_head_attention(queries, keys, values, attn_bias, d_key, d_value,
                         d_model, n_head=1, dropout_rate=0.0, cache=None,
                         param_initializer=None, name="multi_head_att",
                         is_test=False, causal=False, attn_impl="auto"):
    """The transformer MHA block used by ERNIE/BERT/Transformer models
    (mirrors PaddlePaddle/models transformer.multi_head_attention).
    attn_impl routes the fused attention op ("auto" | "xla" | "flash" |
    "ring" | "ulysses") — the sequence-parallel paths accept attn_bias
    key-padding masks (BERT's (N,1,1,T) bias rides the ring with K/V)."""
    keys = queries if keys is None else keys
    values = keys if values is None else values

    def _attr(suffix):
        return ParamAttr(name=None if name is None else name + suffix,
                         initializer=param_initializer)

    def _split_heads(x, dh):
        r = reshape(x, [0, -1 if x.shape[1] == -1 else x.shape[1],
                        n_head, dh])
        return transpose(r, [0, 2, 1, 3])

    q = fc(queries, d_key * n_head, num_flatten_dims=2,
           param_attr=_attr("_query_fc.w_0"), bias_attr=_attr("_query_fc.b_0"))
    qh = _split_heads(q, d_key)

    if cache is not None and "static_k" in cache:
        # cross-attention with precomputed encoder K/V (see mha_kv_projection)
        kh, vh = cache["static_k"], cache["static_v"]
    else:
        kh, vh = mha_kv_projection(keys, values, d_key, d_value, n_head,
                                   param_initializer=param_initializer,
                                   name=name)
        if cache is not None:
            # incremental self-attention: append this step's K/V to the cache
            # (reference: PaddlePaddle/models transformer fast_decoder cache)
            if cache.get("k") is not None:
                kh = concat([cache["k"], kh], axis=2)
                vh = concat([cache["v"], vh], axis=2)
            cache["k"], cache["v"] = kh, vh
            if queries.shape[1] == 1:
                causal = False    # single newest query sees the whole cache
    ctx = fused_attention(qh, kh, vh, mask=attn_bias,
                          scale=d_key ** -0.5, causal=causal,
                          impl=attn_impl)
    ctx = transpose(ctx, [0, 2, 1, 3])
    ctx = reshape(ctx, [0, -1 if queries.shape[1] == -1 else queries.shape[1],
                        d_value * n_head])
    if dropout_rate:
        ctx = dropout(ctx, dropout_rate, is_test=is_test,
                      dropout_implementation="upscale_in_train")
    out = fc(ctx, d_model, num_flatten_dims=2,
             param_attr=_attr("_output_fc.w_0"),
             bias_attr=_attr("_output_fc.b_0"))
    return out
