"""Input layers.

Reference parity: python/paddle/fluid/layers/io.py (data) + fluid.data.
"""
from ..framework.program import default_main_program, default_startup_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare a feed variable. append_batch_size=True prepends -1 (batch),
    matching fluid.layers.data; fluid.data (v1.6+) passes the full shape."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    for prog in (default_main_program(),):
        blk = prog.global_block()
        var = blk.create_var(name=name, shape=tuple(shape), dtype=dtype,
                             is_data=True, stop_gradient=stop_gradient,
                             lod_level=lod_level)
    return var
