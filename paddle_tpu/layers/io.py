"""Input layers.

Reference parity: python/paddle/fluid/layers/io.py (data) + fluid.data.
"""
from ..framework.program import default_main_program, default_startup_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare a feed variable. append_batch_size=True prepends -1 (batch),
    matching fluid.layers.data; fluid.data (v1.6+) passes the full shape."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    for prog in (default_main_program(),):
        blk = prog.global_block()
        var = blk.create_var(name=name, shape=tuple(shape), dtype=dtype,
                             is_data=True, stop_gradient=stop_gradient,
                             lod_level=lod_level)
    return var


class EOFException(Exception):
    """Raised by Executor.run when a started py_reader runs dry
    (reference fluid.core.EOFException); catch it and reader.reset()."""


class PyReader(object):
    """In-graph reader queue (reference layers/io.py:547 py_reader +
    operators/reader/create_py_reader_op). TPU-native: a background thread
    prefetches decorated batches into a bounded queue (the double buffer);
    Executor.run pulls the next batch for this reader's variables when the
    caller does not feed them — the same run-without-feed training loop
    fluid scripts use, minus the C++ blocking queue."""

    def __init__(self, capacity, shapes=None, dtypes=None, lod_levels=None,
                 name=None, use_double_buffer=True, feed_list=None):
        import queue as _queue
        from ..framework import unique_name
        if feed_list is not None:       # wrap EXISTING data Variables
            self._vars = list(feed_list)
            self._names = [v.name for v in self._vars]
        else:
            base = name or unique_name.generate("py_reader")
            self._names = ["%s_slot_%d" % (base, i)
                           for i in range(len(shapes))]
            self._vars = [data(n, list(s), dtype=d,
                               append_batch_size=False)
                          for n, s, d in zip(self._names, shapes, dtypes)]
        # the host-side queue always honours the requested capacity;
        # use_double_buffer in the reference only adds the device staging
        # slot, which here is Executor._convert_feed's device_put
        self._capacity = max(2, int(capacity))
        self._queue = _queue.Queue(self._capacity)
        self._pushback = []
        self._generator = None
        self._thread = None
        self._started = False
        prog = default_main_program()
        if not hasattr(prog, "_py_readers"):
            prog._py_readers = []
        prog._py_readers.append(self)

    # ---- decoration (reference decorate_* methods) -------------------
    def decorate_paddle_reader(self, reader):
        """reader() yields batches as lists of per-sample tuples."""
        import numpy as np

        def gen():
            for samples in reader():
                cols = list(zip(*samples))
                yield tuple(np.stack([np.asarray(c) for c in col])
                            for col in cols)
        self._generator = gen
        return self

    decorate_sample_list_generator = decorate_paddle_reader

    def decorate_tensor_provider(self, reader):
        """reader() yields ready batch tuples of arrays."""
        self._generator = reader
        return self

    decorate_batch_generator = decorate_tensor_provider

    # ---- queue control ----------------------------------------------
    def start(self):
        import threading
        if self._generator is None:
            raise RuntimeError("py_reader.start(): decorate a reader first")
        if self._started:
            return
        self._started = True
        self._stop = False

        def _fill():
            try:
                for batch in self._generator():
                    if self._stop:
                        return
                    self._queue.put(tuple(batch))
            finally:
                self._queue.put(None)   # EOF sentinel

        self._thread = threading.Thread(target=_fill, daemon=True)
        self._thread.start()

    def _drain(self):
        import queue as _queue
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass

    def reset(self):
        self._stop = True
        if self._thread is not None:
            # drain WHILE joining so a filler blocked on a full queue can
            # finish its pending put (incl. the EOF sentinel) before we do
            # the final drain — otherwise a stale batch/None survives into
            # the next epoch
            while self._thread.is_alive():
                self._drain()
                self._thread.join(timeout=0.1)
            self._thread = None
        self._drain()
        self._pushback = []
        self._started = False

    def _push_back(self, feed_dict):
        """Return an already-dequeued batch (used when a sibling reader
        hits EOF in the same run, so no data is lost)."""
        self._pushback.append(feed_dict)

    def _next_feed(self):
        if not self._started:
            raise RuntimeError("py_reader: call start() before exe.run")
        if self._pushback:
            return self._pushback.pop()
        batch = self._queue.get()
        if batch is None:
            self._started = False
            raise EOFException("py_reader %s exhausted" % self._names[0])
        if len(batch) != len(self._names):
            raise ValueError("py_reader got %d arrays for %d slots"
                             % (len(batch), len(self._names)))
        return dict(zip(self._names, batch))


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    return PyReader(capacity, shapes, dtypes, lod_levels, name,
                    use_double_buffer)


def read_file(reader):
    """Unpack a py_reader into its data Variables (reference read_file)."""
    if len(reader._vars) == 1:
        return reader._vars[0]
    return list(reader._vars)


def double_buffer(reader, place=None, name=None):
    """Parity wrapper: PyReader already double-buffers host-side via its
    bounded prefetch queue + JAX async dispatch (reference double_buffer
    staged batches to GPU memory; device_put staging happens in
    Executor._convert_feed)."""
    return reader


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """PyReader wired to EXISTING data Variables (ref layers/io.py
    create_py_reader_by_data) — batches from the decorated reader feed
    those variables by name."""
    return PyReader(capacity, name=name, use_double_buffer=use_double_buffer,
                    feed_list=feed_list)


def load(out, file_path, load_as_fp16=False):
    """Load one saved tensor into `out` (ref layers/io.py load / load_op).
    Reads a .npy written by layers-level save or numpy."""
    prog = default_main_program()
    blk = prog.current_block()
    blk.append_op("load_tensor", inputs={},
                  outputs={"Out": [out.name]},
                  attrs={"file_path": str(file_path),
                         "load_as_fp16": bool(load_as_fp16)})
    return out
