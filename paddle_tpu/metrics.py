"""Python-side streaming metrics.

Reference parity: python/paddle/fluid/metrics.py (MetricBase, Accuracy,
Precision, Recall, F1, CompositeMetric, Auc, ChunkEvaluator subset).
"""
import numpy as np


class MetricBase(object):
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if isinstance(v, (int, float)):
                setattr(self, k, 0 if isinstance(v, int) else 0.0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super(Precision, self).__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super(Recall, self).__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class F1(MetricBase):
    def __init__(self, name=None):
        super(F1, self).__init__(name)
        self.p = Precision()
        self.r = Recall()

    def update(self, preds, labels):
        self.p.update(preds, labels)
        self.r.update(preds, labels)

    def eval(self):
        p, r = self.p.eval(), self.r.eval()
        return 2 * p * r / (p + r) if (p + r) else 0.0


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super(Auc, self).__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(num_thresholds + 1, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        score = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.clip((score * self._num_thresholds).astype(np.int64), 0,
                      self._num_thresholds)
        np.add.at(self._stat_pos, idx, (labels > 0).astype(np.int64))
        np.add.at(self._stat_neg, idx, (labels <= 0).astype(np.int64))

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])[::-1].astype(np.float64)
        fp = np.cumsum(self._stat_neg[::-1])[::-1].astype(np.float64)
        tot_pos, tot_neg = tp[0], fp[0]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp_next = np.append(tp[1:], 0.0)
        fp_next = np.append(fp[1:], 0.0)
        area = np.sum((fp - fp_next) * (tp + tp_next) / 2.0)
        return float(area / (tot_pos * tot_neg))


# evaluator-class aliases (ref fluid/metrics.py exposes these names)
from .evaluator import ChunkEvaluator, EditDistance, DetectionMAP  # noqa: E402,F401
