"""Module-path alias for fluid.graphviz (ref
python/paddle/fluid/graphviz.py): DOT rendering lives in debugger.py."""
from .debugger import draw_block_graphviz, draw_program  # noqa: F401

__all__ = ["draw_block_graphviz", "draw_program"]
