"""LoDTensor construction helpers (ref python/paddle/fluid/lod_tensor.py).

The reference's LoDTensor couples a flat value buffer with level-of-
detail offsets.  The TPU-native sequence design is dense ``(batch,
max_len, ...)`` + an explicit ``(batch,)`` length vector (see
layers/sequence_lod.py), so here a "LoDTensor" is a small record
carrying exactly that — plus ``recursive_sequence_lengths()`` /
``lod()`` accessors matching the reference reading of the metadata, so
book scripts that build LoDTensors feed straight into the dense kernels.
"""
import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor",
           "create_random_int_lodtensor"]


class LoDTensor(object):
    """Dense padded data + per-sequence lengths (single LoD level; the
    reference's multi-level nesting flattens into repeated expansion —
    sequence_expand covers that path)."""

    def __init__(self, data=None, lengths=None):
        # no-arg form matches fluid.core.LoDTensor(): build empty, then
        # .set(array, place) / .set_recursive_sequence_lengths(lens)
        self.data = np.asarray(data) if data is not None \
            else np.zeros((0,), np.float32)
        if lengths is None:
            lengths = self._dense_lengths()
        self.lengths = np.asarray(lengths, dtype=np.int64)

    def _dense_lengths(self):
        # dense tensor without ragged structure: every row full length
        if self.data.ndim >= 2:
            return [self.data.shape[1]] * self.data.shape[0]
        return []

    def set(self, array, place=None):
        """fluid.core.LoDTensor().set(np_array, place) parity; place is
        ignored — feeds are staged by the Executor."""
        self.data = np.asarray(array)
        if self.lengths.size == 0:
            self.lengths = np.asarray(self._dense_lengths(), np.int64)
        return self

    def set_recursive_sequence_lengths(self, lens):
        """Length-style LoD; nested levels flatten to tokens-per-outer
        sequence, the same rule as create_lod_tensor."""
        if lens and isinstance(lens[0], (list, tuple)):
            if len(lens) > 1:
                flat, outer, merged, i = lens[-1], lens[0], [], 0
                for n in outer:
                    merged.append(int(np.sum(flat[i:i + n])))
                    i += n
                lens = merged
            else:
                lens = lens[0]
        self.lengths = np.asarray(lens, np.int64)
        return self

    def set_lod(self, lod):
        """Offset-style LoD -> lengths (nested levels flatten like
        set_recursive_sequence_lengths)."""
        nested = lod and isinstance(lod[0], (list, tuple))
        levels = [list(np.diff(np.asarray(l, np.int64)))
                  for l in (lod if nested else [lod])]
        return self.set_recursive_sequence_lengths(levels)

    def recursive_sequence_lengths(self):
        return [list(self.lengths)]

    def lod(self):
        """Offset-style LoD, as the reference stores it."""
        return [list(np.concatenate([[0], np.cumsum(self.lengths)]))]

    def shape(self):
        return self.data.shape

    def __array__(self, dtype=None):
        a = self.data
        return a.astype(dtype) if dtype is not None else a


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Pack ragged rows into the dense+lengths encoding (ref :25).

    ``data`` may be a list of per-sequence lists/arrays, or an ndarray of
    shape (sum(lens), D) to be split per ``recursive_seq_lens`` — both
    reference calling conventions.
    """
    if isinstance(recursive_seq_lens, (list, tuple)) and \
            recursive_seq_lens and \
            isinstance(recursive_seq_lens[0], (list, tuple)):
        if len(recursive_seq_lens) != 1:
            # flatten nested levels: total tokens per outer sequence
            flat = recursive_seq_lens[-1]
            outer = recursive_seq_lens[0]
            lens, i = [], 0
            for n in outer:
                lens.append(int(np.sum(flat[i:i + n])))
                i += n
            recursive_seq_lens = lens
        else:
            recursive_seq_lens = recursive_seq_lens[0]
    lens = [int(l) for l in recursive_seq_lens]

    if isinstance(data, np.ndarray):
        rows = np.split(data, np.cumsum(lens)[:-1], axis=0)
    else:
        rows = [np.asarray(r) for r in data]
        if rows and rows[0].ndim == 1:
            rows = [r[:, None] for r in rows]
    assert len(rows) == len(lens), \
        "rows (%d) vs recursive_seq_lens (%d)" % (len(rows), len(lens))
    max_len = max(lens) if lens else 0
    feat = rows[0].shape[1:] if rows else ()
    out = np.zeros((len(rows), max_len) + tuple(feat), rows[0].dtype
                   if rows else np.float32)
    for i, (r, l) in enumerate(zip(rows, lens)):
        out[i, :l] = r[:l]
    return LoDTensor(out, lens)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=10):
    """Random-int LoDTensor with the given ragged layout (ref :102)."""
    lens = recursive_seq_lens[0] if (
        recursive_seq_lens and
        isinstance(recursive_seq_lens[0], (list, tuple))) \
        else recursive_seq_lens
    rows = [np.random.randint(low, high + 1,
                              size=(int(l),) + tuple(base_shape))
            for l in lens]
    return create_lod_tensor(rows, [list(lens)], place)
