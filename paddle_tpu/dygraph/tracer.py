"""fluid.dygraph.tracer parity: the eager tape that records ops for
backward lives in dygraph/base.py; Tracer exposes its handle."""
from . import base as _base

__all__ = ["Tracer"]


class Tracer(object):
    """Reference Tracer wraps the C++ imperative tracer; here the tape
    (dygraph/base.py) is the recording machinery."""

    def __init__(self, block=None):
        self._block = block

    @property
    def tape(self):
        return _base._tape
