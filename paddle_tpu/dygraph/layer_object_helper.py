"""fluid.dygraph.layer_object_helper parity: one LayerHelper serves
both modes here."""
from ..layer_helper import LayerHelper as LayerObjectHelper  # noqa: F401

__all__ = ["LayerObjectHelper"]
