"""Dygraph nn modules.

Reference parity: dygraph/nn.py (Conv2D, Pool2D, FC/Linear, BatchNorm,
Embedding, LayerNorm, GRUUnit, Dropout ...). Forward math calls the SAME op
kernels as graph mode (ops/*), eagerly.
"""
import numpy as np
import jax
import jax.numpy as jnp

from .base import EagerVariable, apply_eager
from .layers import Layer
from ..ops.registry import get_op


class _EagerCtx(object):
    """Minimal ctx for running op kernels eagerly."""
    def __init__(self, seed=None):
        import jax
        self._key = jax.random.PRNGKey(
            np.random.randint(0, 2**31) if seed is None else seed)
        self._n = 0

    def rng(self):
        import jax
        self._n += 1
        return jax.random.fold_in(self._key, self._n)


def run_op(op_type, ins, attrs=None, ctx=None, out_binding=None):
    """Eagerly run a registered kernel on EagerVariables/arrays, recording
    a tape node (reference: imperative tracer TraceOp) so .backward()
    reaches through it. Differentiable slots follow the registry's nondiff
    metadata — the same partition the static trace engine uses.
    out_binding: {slot: [EagerVariable]} — bind results onto existing
    placeholder variables (the LayerHelper eager path) instead of
    allocating fresh ones."""
    from .base import _should_record, record_node
    kernel = get_op(op_type)
    evs = {k: [v if isinstance(v, EagerVariable)
               else EagerVariable(v, stop_gradient=True) for v in vs]
           for k, vs in ins.items()}
    jins = {k: [v._value for v in vs] for k, vs in evs.items()}
    attrs = attrs or {}
    ctx = ctx or _EagerCtx()

    flat_vars = []
    flat_slots = []
    for slot in sorted(evs):
        if slot in kernel.nondiff:
            continue
        for i, v in enumerate(evs[slot]):
            flat_vars.append(v)
            flat_slots.append((slot, i))

    def _bindvar(k, i, raw):
        bound = (out_binding or {}).get(k)
        if bound is not None and i < len(bound):
            bound[i]._value = raw
            return bound[i]
        return EagerVariable(raw)

    def _wrap(outs, listy):
        return {k: ([_bindvar(k, i, x) for i, x in enumerate(v)]
                    if listy[k] else _bindvar(k, 0, v[0]))
                for k, v in outs.items()}

    listy = {}

    def pure(*flat_vals):
        ins2 = {k: list(vs) for k, vs in jins.items()}
        for (slot, i), v in zip(flat_slots, flat_vals):
            ins2[slot][i] = v
        outs = kernel.fn(ctx, ins2, attrs)
        for k, v in outs.items():
            listy[k] = isinstance(v, (list, tuple))
        return {k: (list(v) if isinstance(v, (list, tuple)) else [v])
                for k, v in outs.items()}

    if not (kernel.differentiable and _should_record(flat_vars)):
        outs = pure(*[v._value for v in flat_vars])
        return _wrap(outs, listy)

    outs, vjp_fn = jax.vjp(pure, *[v._value for v in flat_vars])
    wrapped = _wrap(outs, listy)
    out_vars = []
    for k in sorted(outs):
        vs = wrapped[k]
        out_vars.extend(vs if isinstance(vs, list) else [vs])

    def dict_vjp(out_cots, _keys=sorted(outs),
                 _shapes={k: len(outs[k]) for k in outs}):
        # re-nest the flat cotangent list to the dict-of-lists structure
        it = iter(out_cots)
        cot = {k: [next(it) for _ in range(_shapes[k])] for k in _keys}
        return vjp_fn(cot)

    record_node(dict_vjp, flat_vars, out_vars)
    return wrapped


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super(Linear, self).__init__(dtype=dtype)
        self.weight = self.add_parameter(
            "weight", self.create_parameter([input_dim, output_dim],
                                            attr=param_attr))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([output_dim], is_bias=True,
                                          attr=bias_attr))
        self._act = act

    def forward(self, input):
        out = apply_eager(lambda x, w, b: jnp.matmul(x, w) + b,
                          input, self.weight, self.bias)
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super(Conv2D, self).__init__(dtype=dtype)
        fs = [filter_size] * 2 if isinstance(filter_size, int) \
            else list(filter_size)
        std = (2.0 / (fs[0] * fs[1] * num_channels)) ** 0.5
        w = np.random.normal(
            0, std, [num_filters, num_channels // groups] + fs
        ).astype(np.float32)
        self.weight = self.add_parameter("weight", EagerVariable(w))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], is_bias=True))
        self._attrs = {"strides": [stride] * 2 if isinstance(stride, int)
                       else list(stride),
                       "paddings": [padding] * 2 if isinstance(padding, int)
                       else list(padding),
                       "dilations": [dilation] * 2
                       if isinstance(dilation, int) else list(dilation),
                       "groups": groups}
        self._act = act

    def forward(self, input):
        out = run_op("conv2d", {"Input": [input], "Filter": [self.weight]},
                     self._attrs)["Output"]
        out = apply_eager(lambda o, b: o + b.reshape(1, -1, 1, 1),
                          out, self.bias)
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, ceil_mode=False,
                 exclusive=True):
        super(Pool2D, self).__init__()
        self._attrs = {"ksize": [pool_size] * 2
                       if isinstance(pool_size, int) else list(pool_size),
                       "pooling_type": pool_type,
                       "strides": [pool_stride] * 2
                       if isinstance(pool_stride, int) else list(pool_stride),
                       "paddings": [pool_padding] * 2
                       if isinstance(pool_padding, int)
                       else list(pool_padding),
                       "global_pooling": global_pooling,
                       "exclusive": exclusive}

    def forward(self, input):
        return run_op("pool2d", {"X": [input]}, self._attrs)["Out"]


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW",
                 use_global_stats=False):
        super(BatchNorm, self).__init__(dtype=dtype)
        c = num_channels
        self.weight = self.add_parameter(
            "weight", EagerVariable(np.ones(c, np.float32)))
        self.bias = self.add_parameter(
            "bias", EagerVariable(np.zeros(c, np.float32)))
        self._mean = EagerVariable(np.zeros(c, np.float32),
                                   stop_gradient=True)
        self._variance = EagerVariable(np.ones(c, np.float32),
                                       stop_gradient=True)
        self._attrs = {"momentum": momentum, "epsilon": epsilon,
                       "data_layout": data_layout,
                       "use_global_stats": use_global_stats}
        self._act = act

    def forward(self, input):
        attrs = dict(self._attrs)
        attrs["is_test"] = not self.training
        outs = run_op("batch_norm",
                      {"X": [input], "Scale": [self.weight],
                       "Bias": [self.bias], "Mean": [self._mean],
                       "Variance": [self._variance]}, attrs)
        self._mean._value = outs["MeanOut"]._value
        self._variance._value = outs["VarianceOut"]._value
        out = outs["Y"]
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None,
                 param_attr=None, dtype="float32"):
        super(Embedding, self).__init__(dtype=dtype)
        w = np.random.normal(0, 0.02, size).astype(np.float32)
        self.weight = self.add_parameter("weight", EagerVariable(w))
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, input):
        return run_op("lookup_table",
                      {"W": [self.weight], "Ids": [input]},
                      {"padding_idx": self._padding_idx})["Out"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super(LayerNorm, self).__init__(dtype=dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self.weight = self.add_parameter(
            "weight", EagerVariable(np.ones(n, np.float32)))
        self.bias = self.add_parameter(
            "bias", EagerVariable(np.zeros(n, np.float32)))
        self._epsilon = epsilon
        self._act = act

    def forward(self, input):
        out = run_op("layer_norm",
                     {"X": [input], "Scale": [self.weight],
                      "Bias": [self.bias]},
                     {"epsilon": self._epsilon,
                      "begin_norm_axis": len(input.shape) - 1})["Y"]
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class Dropout(Layer):
    def __init__(self, p=0.5, mode="downgrade_in_infer"):
        super(Dropout, self).__init__()
        self._p = p
        self._mode = mode

    def forward(self, input):
        return run_op("dropout", {"X": [input]},
                      {"dropout_prob": self._p,
                       "is_test": not self.training,
                       "dropout_implementation": self._mode})["Out"]


class GRUUnit(Layer):
    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 dtype="float32"):
        super(GRUUnit, self).__init__(dtype=dtype)
        h = size // 3
        self.weight = self.add_parameter(
            "weight", self.create_parameter([h, 3 * h]))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([3 * h], is_bias=True))
        self._attrs = {"activation": activation,
                       "gate_activation": gate_activation}

    def forward(self, input, hidden):
        outs = run_op("gru_unit",
                      {"Input": [input], "HiddenPrev": [hidden],
                       "Weight": [self.weight], "Bias": [self.bias]},
                      self._attrs)
        return outs["Hidden"], outs["ResetHiddenPrev"], outs["Gate"]


class FC(Layer):
    """Multi-dim fc (ref dygraph/nn.py:960): flattens input from
    num_flatten_dims on, like the static fc."""

    def __init__(self, name_scope, size, num_flatten_dims=1,
                 param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super(FC, self).__init__(dtype=dtype)
        self._size = size
        self._nfd = num_flatten_dims
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self._built = False

    def _build_once(self, shape):
        d = int(np.prod(shape[self._nfd:]))
        self.weight = self.add_parameter(
            "weight", self.create_parameter([d, self._size],
                                            attr=self._param_attr))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([self._size], is_bias=True,
                                          attr=self._bias_attr))
        self._built = True

    def forward(self, input):
        shp = input.shape() if callable(getattr(input, "shape", None)) \
            else input.shape
        if not self._built:
            self._build_once(tuple(shp))
        nfd = self._nfd

        def fc(x, w, b):
            lead = x.shape[:nfd]
            flat = x.reshape(lead + (-1,))
            return jnp.matmul(flat, w) + b

        out = apply_eager(fc, input, self.weight, self.bias)
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class Conv2DTranspose(Layer):
    """ref dygraph/nn.py:2282 — transposed conv via the graph kernel."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super(Conv2DTranspose, self).__init__(dtype=dtype)
        fs = [filter_size] * 2 if isinstance(filter_size, int) \
            else list(filter_size)
        w = np.random.normal(
            0, 0.02, [num_channels, num_filters // groups] + fs
        ).astype(np.float32)
        self.weight = self.add_parameter("weight", EagerVariable(w))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], is_bias=True))
        self._attrs = {
            "strides": [stride] * 2 if isinstance(stride, int)
            else list(stride),
            "paddings": [padding] * 2 if isinstance(padding, int)
            else list(padding),
            "dilations": [dilation] * 2 if isinstance(dilation, int)
            else list(dilation),
            "groups": groups}
        self._act = act

    def forward(self, input):
        out = run_op("conv2d_transpose",
                     {"Input": [input], "Filter": [self.weight]},
                     self._attrs)["Output"]
        out = apply_eager(lambda o, b: o + b.reshape(1, -1, 1, 1),
                          out, self.bias)
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class Conv3D(Layer):
    """ref dygraph/nn.py:273."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super(Conv3D, self).__init__(dtype=dtype)
        fs = [filter_size] * 3 if isinstance(filter_size, int) \
            else list(filter_size)
        w = np.random.normal(
            0, 0.02, [num_filters, num_channels // groups] + fs
        ).astype(np.float32)
        self.weight = self.add_parameter("weight", EagerVariable(w))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], is_bias=True))
        self._attrs = {
            "strides": [stride] * 3 if isinstance(stride, int)
            else list(stride),
            "paddings": [padding] * 3 if isinstance(padding, int)
            else list(padding),
            "dilations": [dilation] * 3 if isinstance(dilation, int)
            else list(dilation),
            "groups": groups}
        self._act = act

    def forward(self, input):
        out = run_op("conv3d",
                     {"Input": [input], "Filter": [self.weight]},
                     self._attrs)["Output"]
        out = apply_eager(lambda o, b: o + b.reshape(1, -1, 1, 1, 1),
                          out, self.bias)
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class Conv3DTranspose(Layer):
    """ref dygraph/nn.py:475."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super(Conv3DTranspose, self).__init__(dtype=dtype)
        fs = [filter_size] * 3 if isinstance(filter_size, int) \
            else list(filter_size)
        w = np.random.normal(
            0, 0.02, [num_channels, num_filters // groups] + fs
        ).astype(np.float32)
        self.weight = self.add_parameter("weight", EagerVariable(w))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], is_bias=True))
        self._attrs = {
            "strides": [stride] * 3 if isinstance(stride, int)
            else list(stride),
            "paddings": [padding] * 3 if isinstance(padding, int)
            else list(padding),
            "dilations": [dilation] * 3 if isinstance(dilation, int)
            else list(dilation),
            "groups": groups}
        self._act = act

    def forward(self, input):
        out = run_op("conv3d_transpose",
                     {"Input": [input], "Filter": [self.weight]},
                     self._attrs)["Output"]
        out = apply_eager(lambda o, b: o + b.reshape(1, -1, 1, 1, 1),
                          out, self.bias)
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class GroupNorm(Layer):
    """ref dygraph/nn.py:2672."""

    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super(GroupNorm, self).__init__(dtype=dtype)
        self.weight = self.add_parameter(
            "weight", EagerVariable(np.ones(channels, np.float32)))
        self.bias = self.add_parameter(
            "bias", EagerVariable(np.zeros(channels, np.float32)))
        self._attrs = {"groups": groups, "epsilon": epsilon}
        self._act = act

    def forward(self, input):
        out = run_op("group_norm",
                     {"X": [input], "Scale": [self.weight],
                      "Bias": [self.bias]}, self._attrs)["Y"]
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class SpectralNorm(Layer):
    """ref dygraph/nn.py:2772 — power-iteration U/V kept as buffers."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super(SpectralNorm, self).__init__(dtype=dtype)
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self._u = EagerVariable(
            np.random.normal(0, 1, h).astype(np.float32))
        self._v = EagerVariable(
            np.random.normal(0, 1, w).astype(np.float32))
        self._attrs = {"dim": dim, "power_iters": power_iters, "eps": eps}

    def forward(self, weight):
        outs = run_op("spectral_norm",
                      {"Weight": [weight], "U": [self._u],
                       "V": [self._v]}, self._attrs)
        # persist the power-iteration state so sigma converges across
        # calls (the static path writes UOut/VOut back the same way)
        self._u._value = outs["UOut"]._value \
            if hasattr(outs["UOut"], "_value") else outs["UOut"]
        self._v._value = outs["VOut"]._value \
            if hasattr(outs["VOut"], "_value") else outs["VOut"]
        return outs["Out"]


class PRelu(Layer):
    """ref dygraph/nn.py:2092 — mode in all/channel/element."""

    def __init__(self, mode, input_shape=None, param_attr=None,
                 dtype="float32"):
        super(PRelu, self).__init__(dtype=dtype)
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            assert input_shape is not None, \
                "channel mode needs input_shape"
            shape = [input_shape[1] if len(input_shape) > 1
                     else input_shape[0]]
        elif mode == "element":
            assert input_shape is not None, \
                "element mode needs input_shape"
            shape = list(input_shape[1:])
        else:
            raise ValueError("mode must be all/channel/element")
        self.weight = self.add_parameter(
            "weight",
            EagerVariable(np.full(shape, 0.25, np.float32)))
        self._shape = shape

    def forward(self, input):
        mode = self._mode

        def prelu(x, a):
            if mode == "channel":
                a = a.reshape((1, -1) + (1,) * (x.ndim - 2))
            elif mode == "element":
                a = a.reshape((1,) + a.shape)
            return jnp.where(x > 0, x, a * x)

        return apply_eager(prelu, input, self.weight)


class NCE(Layer):
    """ref dygraph/nn.py:1858 — NCE loss head over (input, label)."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler="uniform", custom_dist=None, seed=0,
                 is_sparse=False, dtype="float32"):
        super(NCE, self).__init__(dtype=dtype)
        if custom_dist is not None or sampler == "custom_dist":
            raise NotImplementedError(
                "NCE custom_dist sampling is not implemented; supported "
                "samplers: uniform, log_uniform")
        if sample_weight is not None:
            raise NotImplementedError(
                "NCE sample_weight is not implemented")
        self.weight = self.add_parameter(
            "weight", self.create_parameter([num_total_classes, dim]))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_total_classes],
                                          is_bias=True))
        self._attrs = {"num_total_classes": num_total_classes,
                       "num_neg_samples": num_neg_samples,
                       "sampler": sampler}

    def forward(self, input, label, sample_weight=None):
        if sample_weight is not None:
            raise NotImplementedError(
                "NCE sample_weight is not implemented")
        return run_op("nce",
                      {"Input": [input], "Label": [label],
                       "Weight": [self.weight], "Bias": [self.bias]},
                      self._attrs)["Cost"]


class BilinearTensorProduct(Layer):
    """ref dygraph/nn.py:2174: out_i = x W_i y^T."""

    def __init__(self, input1_dim, input2_dim, output_dim,
                 param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super(BilinearTensorProduct, self).__init__(dtype=dtype)
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [output_dim, input1_dim, input2_dim]))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([output_dim], is_bias=True))
        self._act = act

    def forward(self, x, y):
        out = run_op("bilinear_tensor_product",
                     {"X": [x], "Y": [y], "Weight": [self.weight],
                      "Bias": [self.bias]})["Out"]
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class RowConv(Layer):
    """ref dygraph/nn.py:2593 — lookahead conv on (B, T, D)."""

    def __init__(self, name_scope, future_context_size, param_attr=None,
                 act=None, dtype="float32"):
        super(RowConv, self).__init__(dtype=dtype)
        self._k = future_context_size
        self._act = act
        self._built = False

    def _build_once(self, d):
        self.weight = self.add_parameter(
            "weight", self.create_parameter([self._k + 1, d]))
        self._built = True

    def forward(self, input):
        if not self._built:
            shp = input.shape() if callable(getattr(input, "shape", None))\
                else input.shape
            self._build_once(shp[-1])
        out = run_op("row_conv",
                     {"X": [input], "Filter": [self.weight]})["Out"]
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class SequenceConv(Layer):
    """ref dygraph/nn.py:2499 — centered context-window conv over time:
    im2col the +-window then one matmul (dense (B, T, D) batches)."""

    def __init__(self, name_scope, num_filters, filter_size=3,
                 filter_stride=1, padding=True, bias_attr=None,
                 param_attr=None, act=None, dtype="float32"):
        super(SequenceConv, self).__init__(dtype=dtype)
        assert filter_stride == 1, "reference enforces stride 1"
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._act = act
        self._built = False

    def _build_once(self, d):
        self.weight = self.add_parameter(
            "weight",
            self.create_parameter([self._filter_size * d,
                                   self._num_filters]))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([self._num_filters],
                                          is_bias=True))
        self._built = True

    def forward(self, input):
        if not self._built:
            shp = input.shape() if callable(getattr(input, "shape", None))\
                else input.shape
            self._build_once(shp[-1])
        fs = self._filter_size
        start = -((fs - 1) // 2)

        def seq_conv(x, w, b):
            bsz, t, d = x.shape
            cols = []
            for k in range(fs):
                off = start + k
                if off < 0:
                    sl = jnp.concatenate(
                        [jnp.zeros((bsz, -off, d), x.dtype),
                         x[:, :t + off]], axis=1)
                elif off > 0:
                    sl = jnp.concatenate(
                        [x[:, off:], jnp.zeros((bsz, off, d), x.dtype)],
                        axis=1)
                else:
                    sl = x
                cols.append(sl)
            windows = jnp.concatenate(cols, axis=2)   # (B, T, fs*D)
            return jnp.matmul(windows, w) + b

        out = apply_eager(seq_conv, input, self.weight, self.bias)
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out


class TreeConv(Layer):
    """ref dygraph/nn.py:2877 — TBCNN over (nodes, edge_set)."""

    def __init__(self, name_scope, output_size, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None,
                 bias_attr=None, dtype="float32"):
        super(TreeConv, self).__init__(dtype=dtype)
        self._output_size = output_size
        self._num_filters = num_filters
        self._max_depth = max_depth
        self._act = act
        self._built = False

    def _build_once(self, f):
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [f, 3, self._output_size, self._num_filters]))
        self._built = True

    def forward(self, nodes_vector, edge_set):
        if not self._built:
            shp = nodes_vector.shape() if callable(
                getattr(nodes_vector, "shape", None)) \
                else nodes_vector.shape
            self._build_once(shp[-1])
        out = run_op("tree_conv",
                     {"NodesVector": [nodes_vector],
                      "EdgeSet": [edge_set],
                      "Filter": [self.weight]},
                     {"max_depth": self._max_depth})["Out"]
        if self._act:
            out = run_op(self._act, {"X": [out]})["Out"]
        return out
