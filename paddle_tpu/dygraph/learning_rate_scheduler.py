"""Dygraph LR schedules
(ref python/paddle/fluid/dygraph/learning_rate_scheduler.py).

Callable decay objects: each optimizer step calls the object, which
returns the current LR and advances its step counter — pass one as the
``learning_rate`` of any paddle_tpu.dygraph.optimizers optimizer (they
already accept callables).  Formulas mirror the static-graph
layers/learning_rate_scheduler.py family.
"""
import math

__all__ = ['PiecewiseDecay', 'NaturalExpDecay', 'ExponentialDecay',
           'InverseTimeDecay', 'PolynomialDecay', 'CosineDecay',
           'NoamDecay', 'LinearLrWarmup']


class LearningRateDecay(object):
    """Base (ref :27): __call__ -> current lr, then advance."""

    def __init__(self, begin=0, step=1, dtype='float32'):
        self.step_num = begin
        self.step_size = step
        self.dtype = dtype

    def __call__(self):
        lr = self.step()
        self.step_num += self.step_size
        return float(lr)

    def step(self):
        raise NotImplementedError()


class PiecewiseDecay(LearningRateDecay):
    """boundaries/values staircase (ref :70)."""

    def __init__(self, boundaries, values, begin, step=1, dtype='float32'):
        super(PiecewiseDecay, self).__init__(begin, step, dtype)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def step(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    """lr * e^(-rate * floor_or_frac(step/decay_steps)) (ref :129)."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype='float32'):
        super(NaturalExpDecay, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / float(self.decay_steps)
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate * math.exp(-self.decay_rate * div)


class ExponentialDecay(LearningRateDecay):
    """lr * rate^(step/decay_steps) (ref :208)."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype='float32'):
        super(ExponentialDecay, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / float(self.decay_steps)
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate * self.decay_rate ** div


class InverseTimeDecay(LearningRateDecay):
    """lr / (1 + rate * step/decay_steps) (ref :288)."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype='float32'):
        super(InverseTimeDecay, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / float(self.decay_steps)
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate / (1.0 + self.decay_rate * div)


class PolynomialDecay(LearningRateDecay):
    """Polynomial ramp to end_learning_rate (ref :364)."""

    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1, dtype='float32'):
        super(PolynomialDecay, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.end_learning_rate = end_learning_rate
        self.power = power
        self.cycle = cycle

    def step(self):
        n = self.step_num
        d = self.decay_steps
        if self.cycle:
            mult = max(1.0, math.ceil(n / float(d))) if n else 1.0
            d = d * mult
        else:
            n = min(n, d)
        frac = (1.0 - n / float(d)) ** self.power
        return (self.learning_rate - self.end_learning_rate) * frac + \
            self.end_learning_rate


class CosineDecay(LearningRateDecay):
    """Half-cosine over epochs (ref :456)."""

    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype='float32'):
        super(CosineDecay, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def step(self):
        cur_epoch = math.floor(self.step_num / float(self.step_each_epoch))
        return self.learning_rate * 0.5 * (
            math.cos(cur_epoch * math.pi / self.epochs) + 1)


class NoamDecay(LearningRateDecay):
    """d_model^-0.5 * min(step^-0.5, step * warmup^-1.5) (ref :512)."""

    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype='float32'):
        super(NoamDecay, self).__init__(begin, step, dtype)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def step(self):
        n = max(self.step_num, 1)
        a = n ** -0.5
        b = n * self.warmup_steps ** -1.5
        return self.d_model ** -0.5 * min(a, b)


class LinearLrWarmup(LearningRateDecay):
    """Linear warmup wrapping a base lr or another decay (ref :566)."""

    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 begin=1, step=1, dtype='float32'):
        super(LinearLrWarmup, self).__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr

    def step(self):
        base = self.learning_rate
        # a wrapped decay advances EVERY step — including warmup — so the
        # post-warmup schedule resumes at the right step_num (reference
        # calls base_lr() unconditionally each iteration)
        inner = base() if isinstance(base, LearningRateDecay) else base
        if self.step_num < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * \
                (self.step_num / float(self.warmup_steps))
        return inner
