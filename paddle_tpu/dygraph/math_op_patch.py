"""fluid.dygraph.math_op_patch parity — see layers/math_op_patch.py."""
from ..layers.math_op_patch import monkey_patch_variable \
    as monkey_patch_math_varbase  # noqa: F401

__all__ = ["monkey_patch_math_varbase"]
