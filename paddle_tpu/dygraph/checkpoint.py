"""Dygraph checkpoint save/load (reference: dygraph/checkpoint.py)."""
import os

import numpy as np


def save_dygraph(state_dict, model_path):
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in state_dict.items()}
    np.savez(model_path + ".pdparams.npz", **arrays)


def load_dygraph(model_path):
    path = model_path + ".pdparams.npz"
    with np.load(path, allow_pickle=False) as data:
        return {k: data[k] for k in data.files}, None
