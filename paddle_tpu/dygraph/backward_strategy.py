"""fluid.dygraph.BackwardStrategy parity (ref dygraph/backward_strategy
via core.BackwardStrategy): config holder; the tape always sums
gradients deterministically here, so sort_sum_gradient is recorded but
moot."""
__all__ = ["BackwardStrategy"]


class BackwardStrategy(object):
    def __init__(self):
        self.sort_sum_gradient = False
