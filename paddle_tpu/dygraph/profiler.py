"""Module-path alias for fluid.dygraph.profiler."""
from ..profiler import *  # noqa: F401,F403
from .. import profiler as _p

__all__ = list(getattr(_p, "__all__", []))
