"""Dygraph gradient clipping strategies.

Reference parity: python/paddle/fluid/dygraph_grad_clip.py
(GradClipByValue:46, GradClipByNorm:120, GradClipByGlobalNorm:191). Each
strategy is a callable over [(param, grad_array), ...] pairs returning the
clipped pairs; optimizers apply it via ``minimize(..., grad_clip=clip)``.
Math runs on device as plain jnp ops (fused by XLA when jitted).
"""
import jax.numpy as jnp

__all__ = ["GradClipBase", "GradClipByValue", "GradClipByNorm",
           "GradClipByGlobalNorm"]


class GradClipBase(object):
    def _clip(self, para_and_grad):
        raise NotImplementedError

    def __call__(self, para_and_grad):
        return self._clip(para_and_grad)


class GradClipByValue(GradClipBase):
    """Clamp every gradient element to [min_value, max_value]."""

    def __init__(self, min_value, max_value=None):
        if max_value is None:
            min_value, max_value = -abs(min_value), abs(min_value)
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def __str__(self):
        return "ClipByValue, min=%f, max=%f" % (self.min_value,
                                                self.max_value)

    def _clip(self, para_and_grad):
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, jnp.clip(g, self.min_value, self.max_value)))
        return out


class GradClipByNorm(GradClipBase):
    """Rescale each gradient whose own L2 norm exceeds clip_norm."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __str__(self):
        return "ClipByNorm, clip_norm=%f" % self.clip_norm

    def _clip(self, para_and_grad):
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.where(norm > self.clip_norm,
                              self.clip_norm / jnp.maximum(norm, 1e-12),
                              jnp.ones_like(norm))
            out.append((p, g * scale.astype(g.dtype)))
        return out


class GradClipByGlobalNorm(GradClipBase):
    """Rescale ALL gradients jointly so their global L2 norm is at most
    max_global_norm."""

    def __init__(self, max_global_norm, dtype="float32"):
        self.max_global_norm = float(max_global_norm)
        self.dtype = dtype

    def __str__(self):
        return "ClipByGlobalNorm, max_global_norm=%f" % self.max_global_norm

    def _clip(self, para_and_grad):
        grads = [g for _, g in para_and_grad if g is not None]
        if not grads:
            return list(para_and_grad)
        global_sq = sum(jnp.sum(jnp.square(g.astype(self.dtype)))
                        for g in grads)
        global_norm = jnp.sqrt(global_sq)
        scale = jnp.where(
            global_norm > self.max_global_norm,
            self.max_global_norm / jnp.maximum(global_norm, 1e-12),
            jnp.ones_like(global_norm))
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, g * scale.astype(g.dtype)))
        return out
