"""Dygraph Layer base.

Reference parity: dygraph/layers.py (Layer). Functional-grad design:
``layer.loss_and_grad(loss_fn, *inputs)`` returns (loss, grads-dict) via
jax.value_and_grad over the layer's parameters — the TPU-idiomatic
replacement for tape-based .backward(); minimize() on dygraph optimizers
consumes the grads dict.
"""
import collections

import numpy as np
import jax
import jax.numpy as jnp

from .base import EagerVariable, to_variable


class Layer(object):
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = name_scope or self.__class__.__name__.lower()
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self.training = True

    # ---- naming / registration ------------------------------------------
    def full_name(self):
        return self._full_name

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        if params is not None and isinstance(value, EagerVariable) \
                and getattr(value, "_is_param", False):
            params[name] = value
        elif subs is not None and isinstance(value, Layer):
            subs[name] = value
        object.__setattr__(self, name, value)

    def create_parameter(self, shape, dtype=None, initializer=None,
                         attr=None, is_bias=False):
        from ..initializer import (XavierInitializer, ConstantInitializer,
                                   Initializer)
        dtype = dtype or self._dtype
        init = initializer
        if attr is not None and getattr(attr, "initializer", None):
            init = attr.initializer
        key = np.random.RandomState(len(self._parameters) + 1)
        shape = tuple(int(s) for s in shape)
        if init is None:
            if is_bias:
                value = np.zeros(shape, dtype=np.float32)
            else:
                fan_in = shape[0] if shape else 1
                fan_out = shape[-1] if shape else 1
                limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
                value = key.uniform(-limit, limit, shape).astype(np.float32)
        else:
            value = _materialize_init(init, shape)
        p = EagerVariable(jnp.asarray(value))
        p._is_param = True
        return p

    def add_parameter(self, name, param):
        param._is_param = True
        self._parameters[name] = param
        object.__setattr__(self, name, param)
        return param

    def add_sublayer(self, name, layer):
        self._sub_layers[name] = layer
        object.__setattr__(self, name, layer)
        return layer

    # ---- traversal -------------------------------------------------------
    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def named_parameters(self, prefix=""):
        for n, p in self._parameters.items():
            yield (prefix + n, p)
        for ln, l in self._sub_layers.items():
            for n, p in l.named_parameters(prefix + ln + "."):
                yield (n, p)

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False

    # ---- state dict ------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   prefix=""):
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self.named_parameters(prefix):
            dest[name] = p.numpy()
        return dest

    def set_dict(self, state, include_sublayers=True):
        named = dict(self.named_parameters())
        for name, value in state.items():
            if name in named:
                named[name]._value = jnp.asarray(value)

    load_dict = set_dict

    # ---- calling / autodiff ---------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    def loss_and_grad(self, loss_fn, *inputs):
        """loss_fn(outputs...) -> scalar EagerVariable. Returns
        (loss, {param_id: grad jnp array}) using jax.value_and_grad over a
        functionalized forward."""
        params = self.parameters()
        vals = [p._value for p in params]

        def functional(vals_list, *raw_inputs):
            from .base import pause_tape
            with pause_tape():
                for p, v in zip(params, vals_list):
                    p._value = v
                outs = self.forward(*[to_variable(x) for x in raw_inputs])
                loss = loss_fn(outs) if loss_fn is not None else outs
                return loss._value.reshape(())

        raw = [x._value if isinstance(x, EagerVariable) else jnp.asarray(x)
               for x in inputs]
        try:
            loss_val, grads = jax.value_and_grad(functional)(vals, *raw)
        finally:
            # a trace-time failure must not leave tracers in p._value
            for p, v in zip(params, vals):
                p._value = v
        for p, g in zip(params, grads):
            p._grad = g
        return EagerVariable(loss_val), dict(zip(
            [id(p) for p in params], grads))

    def clear_gradients(self):
        for p in self.parameters():
            p._grad = None


def _materialize_init(init, shape):
    """Run a graph-mode Initializer eagerly to get a numpy value."""
    from ..framework.program import Program, program_guard
    from ..framework.executor import Executor
    from ..framework.scope import Scope, scope_guard
    prog = Program()
    with program_guard(prog, prog):
        blk = prog.global_block()
        var = blk.create_var(name="init_target", shape=shape,
                             dtype="float32", persistable=True)
        init(var, blk)
    scope = Scope()
    with scope_guard(scope):
        Executor().run(prog, feed={}, fetch_list=[])
        return scope.get_numpy("init_target")
