"""fluid.dygraph.dygraph_utils parity (internal helpers)."""
__all__ = ["_append_activation_in_dygraph", "_append_bias_in_dygraph"]


def _append_activation_in_dygraph(input, act=None, use_cudnn=None):
    if act is None:
        return input
    from .. import layers
    return getattr(layers, act)(input)


def _append_bias_in_dygraph(input, bias=None, axis=1):
    if bias is None:
        return input
    from ..layers import elementwise_add
    return elementwise_add(input, bias, axis=axis)
