"""Layer containers (reference: dygraph/container.py)."""
from .layers import Layer


class Sequential(Layer):
    def __init__(self, *layers):
        super(Sequential, self).__init__()
        for i, l in enumerate(layers):
            if isinstance(l, (list, tuple)):
                name, l = l
            else:
                name = str(i)
            self.add_sublayer(name, l)

    def forward(self, input):
        for l in self._sub_layers.values():
            input = l(input)
        return input

    def __getitem__(self, i):
        return list(self._sub_layers.values())[i]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super(LayerList, self).__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def __getitem__(self, i):
        return list(self._sub_layers.values())[i]

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __len__(self):
        return len(self._sub_layers)


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super(ParameterList, self).__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, i):
        return list(self._parameters.values())[i]

    def __iter__(self):
        return iter(self._parameters.values())

    def __len__(self):
        return len(self._parameters)
