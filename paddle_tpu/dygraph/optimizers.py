"""Dygraph optimizers: functional updates over Layer parameter grads.

Reference parity: fluid optimizers used under dygraph.guard (minimize on a
loss Variable with tape grads). Here minimize consumes the grads produced
by Layer.loss_and_grad; update math reuses the SAME op kernels as graph
mode (ops/optimizer_ops.py), jit-compiled per parameter shape.
"""
import jax.numpy as jnp

from ..ops.registry import get_op


class _Ctx:
    def rng(self):
        import jax
        return jax.random.PRNGKey(0)


class DygraphOptimizer(object):
    _op = None

    def __init__(self, learning_rate=0.01, parameter_list=None, **attrs):
        self._lr = learning_rate
        self._params = parameter_list
        self._attrs = attrs
        self._state = {}

    def _lr_value(self):
        lr = self._lr
        if callable(lr):
            lr = lr()
        return jnp.asarray([float(lr)], jnp.float32)

    def _slots(self, p):
        raise NotImplementedError

    def _inputs(self, p, g, slots):
        raise NotImplementedError

    def _apply_outs(self, p, slots, outs):
        raise NotImplementedError

    def minimize(self, layer_or_loss=None, startup_program=None,
                 parameter_list=None, no_grad_set=None, grads=None,
                 grad_clip=None):
        """Positional layout follows fluid's dygraph signature
        minimize(loss, startup_program, parameter_list, no_grad_set):
        minimize(loss_var) after loss.backward() with parameter_list from
        the constructor or this call; minimize(layer) after
        layer.loss_and_grad(...); or minimize(params, grads=grads_dict).
        grad_clip: a dygraph.grad_clip.GradClipBase strategy applied to all
        (param, grad) pairs before the update (ref optimizer.py minimize's
        grad_clip argument in dygraph mode)."""
        from .base import EagerVariable
        if isinstance(startup_program, dict):
            # Old dygraph signature took grads positionally here; silently
            # reading p._grad instead would skip updates without erroring.
            raise TypeError(
                "minimize() got a dict for startup_program — pass eager "
                "gradients via the grads= keyword")
        if hasattr(layer_or_loss, "parameters"):
            params = layer_or_loss.parameters()
        elif isinstance(layer_or_loss, EagerVariable) or layer_or_loss is None:
            params = parameter_list or self._params
            if params is None:
                raise ValueError(
                    "minimize(loss) needs parameter_list — pass it to the "
                    "optimizer constructor (fluid dygraph idiom) or to "
                    "minimize()")
        else:
            params = layer_or_loss
        kernel = get_op(self._op).fn
        pairs = [(p, p._grad if grads is None else grads.get(id(p)))
                 for p in params]
        if grad_clip is not None:
            pairs = grad_clip(pairs)
        for p, g in pairs:
            if g is None:
                continue
            slots = self._state.setdefault(id(p), self._slots(p))
            ins = self._inputs(p, g, slots)
            outs = kernel(_Ctx(), ins, self._attrs)
            self._apply_outs(p, slots, outs)
            p._grad = None


class SGD(DygraphOptimizer):
    _op = "sgd"

    def _slots(self, p):
        return {}

    def _inputs(self, p, g, slots):
        return {"Param": [p._value], "Grad": [g],
                "LearningRate": [self._lr_value()]}

    def _apply_outs(self, p, slots, outs):
        p._value = outs["ParamOut"]


class Momentum(DygraphOptimizer):
    _op = "momentum"

    def __init__(self, learning_rate=0.01, momentum=0.9, **kw):
        super(Momentum, self).__init__(learning_rate, mu=momentum, **kw)

    def _slots(self, p):
        return {"v": jnp.zeros_like(p._value)}

    def _inputs(self, p, g, slots):
        return {"Param": [p._value], "Grad": [g], "Velocity": [slots["v"]],
                "LearningRate": [self._lr_value()]}

    def _apply_outs(self, p, slots, outs):
        p._value = outs["ParamOut"]
        slots["v"] = outs["VelocityOut"]


class Adam(DygraphOptimizer):
    _op = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super(Adam, self).__init__(learning_rate, beta1=beta1, beta2=beta2,
                                   epsilon=epsilon, **kw)
        self._b1, self._b2 = beta1, beta2

    def _slots(self, p):
        return {"m1": jnp.zeros(p._value.shape, jnp.float32),
                "m2": jnp.zeros(p._value.shape, jnp.float32),
                "b1p": jnp.asarray([self._b1], jnp.float32),
                "b2p": jnp.asarray([self._b2], jnp.float32)}

    def _inputs(self, p, g, slots):
        return {"Param": [p._value], "Grad": [g],
                "Moment1": [slots["m1"]], "Moment2": [slots["m2"]],
                "Beta1Pow": [slots["b1p"]], "Beta2Pow": [slots["b2p"]],
                "LearningRate": [self._lr_value()]}

    def _apply_outs(self, p, slots, outs):
        p._value = outs["ParamOut"]
        slots["m1"] = outs["Moment1Out"]
        slots["m2"] = outs["Moment2Out"]
        slots["b1p"] = outs["Beta1PowOut"]
        slots["b2p"] = outs["Beta2PowOut"]


AdamOptimizer = Adam
SGDOptimizer = SGD
MomentumOptimizer = Momentum
