"""Dygraph runtime: eager Variables on jax arrays, with an op tape.

Reference parity: dygraph/base.py + imperative/tracer.cc. Like the
reference's imperative tracer, every eager op records a tape node so
``loss.backward(); opt.minimize(...)`` works verbatim — but each node
stores the op's jax.vjp (JAX linearizes at execution time), so backward is
a pure reverse walk calling stored vjps; no per-op grad kernels exist.
The functional style (Layer.loss_and_grad / jax.grad over a functional
forward) remains available and pauses the tape while tracing.
"""
import contextlib
import functools
import weakref

import numpy as np
import jax
import jax.numpy as jnp

_in_dygraph = [False]
_no_grad_depth = [0]
_tape_paused = [0]
_tape = []
# name -> EagerVariable, so static layer functions (which plumb var NAMES
# through LayerHelper.append_op) can resolve eager values in dygraph mode
_eager_registry = weakref.WeakValueDictionary()
_name_counter = [0]


def lookup_eager(name):
    try:
        return _eager_registry[name]
    except KeyError:
        raise KeyError(
            "dygraph: no eager value named %r — if this is a parameter "
            "from a static layer (fc/conv2d...), use the dygraph.nn "
            "module equivalents under dygraph.guard" % (name,))


class _TapeNode(object):
    """Outputs are held WEAKLY: once every output of a node is garbage
    (no user ref and no later node consumes it), backward can never reach
    the node, so the periodic prune in record_node drops it — this keeps
    forward-only (eval) loops from growing the tape without bound."""
    __slots__ = ("vjp_fn", "in_vars", "out_refs", "out_meta")

    def __init__(self, vjp_fn, in_vars, out_vars):
        self.vjp_fn = vjp_fn        # cotangents(outs) -> grads aligned
        self.in_vars = in_vars      # [EagerVariable] aligned with vjp grads
        self.out_refs = [weakref.ref(v) for v in out_vars]
        self.out_meta = [(v._value.shape, v._value.dtype)
                         for v in out_vars]

    def live(self):
        return any(r() is not None for r in self.out_refs)


_last_prune_size = [256]


def record_node(vjp_fn, in_vars, out_vars):
    _tape.append(_TapeNode(vjp_fn, in_vars, out_vars))
    if len(_tape) >= 2 * _last_prune_size[0]:
        _tape[:] = [n for n in _tape if n.live()]
        _last_prune_size[0] = max(256, len(_tape))


@contextlib.contextmanager
def pause_tape():
    """Disable tape recording (used inside functional jax traces — the
    trace IS the autodiff there, and tracer values must not leak onto the
    global tape)."""
    _tape_paused[0] += 1
    try:
        yield
    finally:
        _tape_paused[0] -= 1


def tape_active():
    return (_in_dygraph[0] and not _tape_paused[0]
            and not _no_grad_depth[0])


def reset_tape():
    del _tape[:]


def _should_record(eager_inputs):
    if not tape_active():
        return False
    for v in eager_inputs:
        if isinstance(v._value, jax.core.Tracer):
            return False  # inside someone else's functional trace
    return any(not v.stop_gradient for v in eager_inputs)


def apply_eager(fn, *eager_inputs):
    """Run fn(*raw_values) eagerly; record a tape node when grads may be
    needed. fn returns one raw array or a tuple; returns EagerVariable(s)
    correspondingly."""
    vals = [v._value for v in eager_inputs]
    if not _should_record(eager_inputs):
        out = fn(*vals)
        if isinstance(out, tuple):
            return tuple(EagerVariable(o) for o in out)
        return EagerVariable(out)
    single = [False]

    def tupled(*a):
        out = fn(*a)
        if not isinstance(out, tuple):
            single[0] = True
            return (out,)
        return out

    outs, vjp_fn = jax.vjp(tupled, *vals)
    out_vars = tuple(EagerVariable(o) for o in outs)
    record_node(vjp_fn, list(eager_inputs), list(out_vars))
    return out_vars[0] if single[0] else out_vars


def _zero_cot(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _backward_from(root, retain_graph=False):
    """Reverse tape walk from ``root`` (scalar or any-shape: seeded with
    ones, as the reference does for non-scalar backward)."""
    cot = {id(root): jnp.ones_like(root._value)}
    keep = {id(root): root}
    claimed = {}  # var id -> (var, final cotangent) once a producer uses it
    for node in reversed(_tape):
        outs = [r() for r in node.out_refs]
        if not any(v is not None and id(v) in cot for v in outs):
            continue
        out_cots = tuple(
            cot[id(v)] if (v is not None and id(v) in cot)
            else _zero_cot(shape, dtype)
            for v, (shape, dtype) in zip(outs, node.out_meta))
        # The producing node CONSUMES its outputs' cotangents: all their
        # consumers sit later in the tape and have already contributed, and
        # popping here prevents double-counting when a variable is bound as
        # the output of more than one node (in-place-style rebinding).
        for v in outs:
            if v is not None and id(v) in cot:
                claimed[id(v)] = (v, cot.pop(id(v)))
        grads = node.vjp_fn(out_cots)
        for var, g in zip(node.in_vars, grads):
            if g is None or (hasattr(g, "dtype")
                             and g.dtype == jax.dtypes.float0):
                continue
            if var.stop_gradient:
                continue
            prev = cot.get(id(var))
            cot[id(var)] = g if prev is None else prev + g
            keep[id(var)] = var
    for vid, var in keep.items():
        if vid in cot:
            claimed[vid] = (var, cot[vid])
    for vid, (var, g) in claimed.items():
        var._grad = g if var._grad is None else var._grad + g
    if not retain_graph:
        reset_tape()


def enabled():
    return _in_dygraph[0]


def enable_dygraph(place=None):
    _in_dygraph[0] = True


def disable_dygraph():
    _in_dygraph[0] = False
    reset_tape()  # mirror guard()'s exit: drop recorded nodes/activations


@contextlib.contextmanager
def guard(place=None):
    old = _in_dygraph[0]
    _in_dygraph[0] = True
    try:
        yield
    finally:
        _in_dygraph[0] = old
        if not old:
            reset_tape()


class EagerVariable(object):
    """Eager tensor: thin wrapper over a jax.Array with fluid's dygraph
    Variable surface (numpy(), backward(), gradient())."""

    def __init__(self, value, name=None, stop_gradient=False):
        self._value = None if value is None else jnp.asarray(value)
        if name is None:
            _name_counter[0] += 1
            name = "eager_var_%d" % _name_counter[0]
        elif name in _eager_registry:
            # user-supplied duplicate: uniquify so name-based op dispatch
            # (LayerHelper eager path) can never resolve to the wrong var
            base, n = name, 1
            while name in _eager_registry:
                n += 1
                name = "%s_%d" % (base, n)
        self.name = name
        self.stop_gradient = stop_gradient
        self._grad = None
        _eager_registry[name] = self

    # value plumbing -------------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return str(self._value.dtype)

    def numpy(self):
        return np.asarray(self._value)

    def astype(self, dtype):
        from ..framework.dtypes import to_jax_dtype
        return apply_eager(
            lambda x: x.astype(to_jax_dtype(dtype)), self)

    def detach(self):
        return EagerVariable(self._value, stop_gradient=True)

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    def backward(self, backward_strategy=None, retain_graph=False):
        """Tape backward (reference: imperative/tracer.cc Engine): fills
        ``._grad`` on every reachable stop_gradient=False Variable, then
        releases the tape."""
        _backward_from(self, retain_graph=retain_graph)

    def clear_gradient(self):
        self._grad = None

    # operator sugar -------------------------------------------------------
    def _b(self, other, fn):
        if isinstance(other, EagerVariable):
            return apply_eager(fn, self, other)
        return apply_eager(lambda a: fn(a, other), self)

    def __add__(self, o):
        return self._b(o, jnp.add)
    __radd__ = __add__

    def __sub__(self, o):
        return self._b(o, jnp.subtract)

    def __rsub__(self, o):
        return self._b(o, lambda a, b: jnp.subtract(b, a))

    def __mul__(self, o):
        return self._b(o, jnp.multiply)
    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._b(o, jnp.divide)

    def __matmul__(self, o):
        return self._b(o, jnp.matmul)

    def __neg__(self):
        return apply_eager(jnp.negative, self)

    def __getitem__(self, idx):
        return apply_eager(lambda x: x[idx], self)

    def __repr__(self):
        return "EagerVariable(%s, shape=%s)" % (self._value, self.shape)


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, EagerVariable):
        return value
    # jnp.asarray in the constructor handles numpy, jax arrays AND tracers
    # (so functionalized forwards can be jitted/grad-ed through)
    return EagerVariable(value, name=name)


@contextlib.contextmanager
def no_grad_ctx():
    _no_grad_depth[0] += 1
    try:
        yield
    finally:
        _no_grad_depth[0] -= 1


def no_grad(fn=None):
    if fn is None:
        return no_grad_ctx()

    @functools.wraps(fn)
    def wrapper(*a, **k):
        with no_grad_ctx():
            return fn(*a, **k)
    return wrapper
