"""Dygraph runtime: eager Variables on jax arrays.

Reference parity: dygraph/base.py + imperative/tracer.cc. The reference
records ops on a tape for autograd; here eager math happens directly on
jax.Arrays and gradients come from jax.grad over Layer.__call__ (see
layers.py), so there is no tape to maintain.
"""
import contextlib
import functools

import numpy as np
import jax.numpy as jnp

_in_dygraph = [False]
_no_grad_depth = [0]


def enabled():
    return _in_dygraph[0]


def enable_dygraph(place=None):
    _in_dygraph[0] = True


def disable_dygraph():
    _in_dygraph[0] = False


@contextlib.contextmanager
def guard(place=None):
    old = _in_dygraph[0]
    _in_dygraph[0] = True
    try:
        yield
    finally:
        _in_dygraph[0] = old


class EagerVariable(object):
    """Eager tensor: thin wrapper over a jax.Array with fluid's dygraph
    Variable surface (numpy(), backward(), gradient())."""

    def __init__(self, value, name=None, stop_gradient=False):
        self._value = jnp.asarray(value)
        self.name = name or "eager_var"
        self.stop_gradient = stop_gradient
        self._grad = None

    # value plumbing -------------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return str(self._value.dtype)

    def numpy(self):
        return np.asarray(self._value)

    def astype(self, dtype):
        from ..framework.dtypes import to_jax_dtype
        return EagerVariable(self._value.astype(to_jax_dtype(dtype)))

    def detach(self):
        return EagerVariable(self._value, stop_gradient=True)

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    def backward(self, backward_strategy=None):
        raise RuntimeError(
            "paddle_tpu dygraph computes gradients functionally: use "
            "dygraph.grad(loss_fn, layer) or Layer.backward helpers "
            "(JAX autodiff replaces the reference's tape)")

    # operator sugar -------------------------------------------------------
    def _b(self, other, fn):
        o = other._value if isinstance(other, EagerVariable) else other
        return EagerVariable(fn(self._value, o))

    def __add__(self, o):
        return self._b(o, jnp.add)
    __radd__ = __add__

    def __sub__(self, o):
        return self._b(o, jnp.subtract)

    def __rsub__(self, o):
        return self._b(o, lambda a, b: jnp.subtract(b, a))

    def __mul__(self, o):
        return self._b(o, jnp.multiply)
    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._b(o, jnp.divide)

    def __matmul__(self, o):
        return self._b(o, jnp.matmul)

    def __neg__(self):
        return EagerVariable(-self._value)

    def __getitem__(self, idx):
        return EagerVariable(self._value[idx])

    def __repr__(self):
        return "EagerVariable(%s, shape=%s)" % (self._value, self.shape)


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, EagerVariable):
        return value
    # jnp.asarray in the constructor handles numpy, jax arrays AND tracers
    # (so functionalized forwards can be jitted/grad-ed through)
    return EagerVariable(value, name=name)


@contextlib.contextmanager
def no_grad_ctx():
    _no_grad_depth[0] += 1
    try:
        yield
    finally:
        _no_grad_depth[0] -= 1


def no_grad(fn=None):
    if fn is None:
        return no_grad_ctx()

    @functools.wraps(fn)
    def wrapper(*a, **k):
        with no_grad_ctx():
            return fn(*a, **k)
    return wrapper
