"""fluid.dygraph.varbase_patch_methods parity — VarBase conveniences
(numpy()/backward()/gradient()) are defined directly on the eager
Variable type here; patching is a verified no-op."""
__all__ = ["monkey_patch_varbase"]


def monkey_patch_varbase():
    pass
