"""Dygraph -> static capture.

Reference parity: dygraph/jit.py (TracedLayer) + ProgramTranslator. Here the
capture IS jax.jit: TracedLayer wraps a dygraph Layer's functional forward
in a jitted callable (one XLA computation), which is also what the static
Executor produces — the two modes converge on the same backend.
"""
import numpy as np
import jax
import jax.numpy as jnp

from .base import EagerVariable, to_variable


class TracedLayer(object):
    def __init__(self, layer, jitted, params):
        self._layer = layer
        self._jitted = jitted
        self._params = params

    @staticmethod
    def trace(layer, inputs):
        params = layer.parameters()

        def functional(param_vals, *raw):
            from .base import pause_tape
            saved = [p._value for p in params]
            try:
                with pause_tape():
                    for p, v in zip(params, param_vals):
                        p._value = v
                    outs = layer.forward(*[to_variable(x) for x in raw])
            finally:
                for p, v in zip(params, saved):
                    p._value = v
            if isinstance(outs, (list, tuple)):
                return tuple(o._value for o in outs)
            return outs._value

        jitted = jax.jit(functional)
        raw = [x._value if isinstance(x, EagerVariable) else jnp.asarray(x)
               for x in inputs]
        out_vals = jitted([p._value for p in params], *raw)
        outs = ([EagerVariable(v) for v in out_vals]
                if isinstance(out_vals, tuple) else EagerVariable(out_vals))
        return outs, TracedLayer(layer, jitted, params)

    def __call__(self, inputs):
        raw = [x._value if isinstance(x, EagerVariable) else jnp.asarray(x)
               for x in inputs]
        out = self._jitted([p._value for p in self._params], *raw)
        if isinstance(out, tuple):
            return [EagerVariable(v) for v in out]
        return EagerVariable(out)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from .checkpoint import save_dygraph
        save_dygraph(self._layer.state_dict(), dirname + "/traced")


def dygraph_to_static_graph(fn):
    """Decorator stub mirroring @dygraph_to_static_graph; functional jit."""
    return fn
