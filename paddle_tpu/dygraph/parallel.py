"""Dygraph data parallelism.

Reference parity: dygraph/parallel.py (DataParallel + Env) — the reference
wraps a Layer, scales the loss, and allreduces grads over NCCL after
backward. TPU-native: one process drives all chips, so DataParallel builds a
pmapped train step: params replicated, batch split over devices, gradients
psum-averaged on ICI inside the step.
"""
import numpy as np
import jax
import jax.numpy as jnp

from .base import EagerVariable, to_variable
from .layers import Layer


class ParallelEnv(object):
    @property
    def nranks(self):
        return jax.device_count()

    @property
    def local_rank(self):
        return jax.process_index()

    @property
    def dev_id(self):
        return 0


def prepare_context(strategy=None):
    return ParallelEnv()


class DataParallel(Layer):
    """Wraps a Layer; train_step(loss_fn, *batch) runs one data-parallel
    SPMD step over all devices and keeps parameters in sync."""

    def __init__(self, layer, strategy=None):
        super(DataParallel, self).__init__()
        self._layers = layer
        self._ndev = jax.device_count()
        self._pstep = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss  # grads are mean-psummed inside the pmapped step

    def apply_collective_grads(self):
        pass  # collective happens inside train_step

    # ------------------------------------------------------------------
    def _functional(self, loss_fn):
        params = self._layers.parameters()

        def fn(param_vals, *raw):
            from .base import pause_tape
            saved = [p._value for p in params]
            try:
                with pause_tape():
                    for p, v in zip(params, param_vals):
                        p._value = v
                    outs = self._layers.forward(
                        *[to_variable(x) for x in raw])
                    loss = loss_fn(outs)
            finally:
                for p, v in zip(params, saved):
                    p._value = v
            return loss._value.reshape(())

        return params, fn

    def train_step(self, loss_fn, optimizer, *batch):
        """One DP step: shards each batch array on dim 0 over devices,
        computes psum-averaged grads, applies `optimizer` (a dygraph
        optimizer) on the synced grads. Returns mean loss."""
        params, fn = self._functional(loss_fn)
        ndev = self._ndev

        if self._pstep is None:
            def pstep(param_vals, *raw):
                loss, grads = jax.value_and_grad(fn)(param_vals, *raw)
                grads = [jax.lax.pmean(g, "dp") for g in grads]
                return jax.lax.pmean(loss, "dp"), grads
            self._pstep = jax.pmap(pstep, axis_name="dp")

        def shard(x):
            x = np.asarray(x)
            return x.reshape((ndev, x.shape[0] // ndev) + x.shape[1:])

        rep = [jnp.broadcast_to(p._value, (ndev,) + p._value.shape)
               for p in params]
        loss, grads = self._pstep(rep, *[shard(b) for b in batch])
        for p, g in zip(params, grads):
            p._grad = g[0]  # identical across devices after pmean
        optimizer.minimize(self._layers)
        return EagerVariable(loss[0])

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)
