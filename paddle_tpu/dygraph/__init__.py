"""Dygraph (eager/imperative) mode.

Reference parity: python/paddle/fluid/dygraph/* + paddle/fluid/imperative/.
TPU-native eager: Variables wrap jax.Arrays directly (no tracer/engine —
JAX IS the tracer); Layer modules hold parameters; backward() uses jax.grad
over the recorded functional call.
"""
from .base import guard, enabled, to_variable, no_grad, enable_dygraph, \
    disable_dygraph, reset_tape, pause_tape
from .layers import Layer
from .container import Sequential, LayerList, ParameterList
from .nn import (Linear, Conv2D, BatchNorm, Embedding, LayerNorm, Dropout,
                 FC, Conv2DTranspose, Conv3D, Conv3DTranspose, GroupNorm,
                 SpectralNorm, PRelu, NCE, BilinearTensorProduct, RowConv,
                 SequenceConv, TreeConv,
                 Pool2D, GRUUnit)
from .checkpoint import save_dygraph, load_dygraph
from .jit import TracedLayer, dygraph_to_static_graph
from . import optimizers
from . import grad_clip
from .grad_clip import GradClipByValue, GradClipByNorm, GradClipByGlobalNorm
from .parallel import DataParallel, ParallelEnv, prepare_context
from . import learning_rate_scheduler
from .learning_rate_scheduler import (PiecewiseDecay, NaturalExpDecay,
    ExponentialDecay, InverseTimeDecay, PolynomialDecay, CosineDecay,
    NoamDecay, LinearLrWarmup)
