"""fluid.dygraph.parallel_helper parity (internal env helpers)."""
import os

__all__ = ["_is_data_parallel_mode", "_is_parallel_ctx_initialized"]


def _is_data_parallel_mode():
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1")) > 1


def _is_parallel_ctx_initialized():
    import jax
    return jax.process_count() > 1
