"""fluid.log_helper parity (ref python/paddle/fluid/log_helper.py)."""
import logging

__all__ = ["get_logger"]


def get_logger(name, level, fmt=None):
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not logger.handlers:
        handler = logging.StreamHandler()
        if fmt:
            handler.setFormatter(logging.Formatter(fmt=fmt))
        logger.addHandler(handler)
    logger.propagate = False
    return logger
