"""Module-path alias for fluid.compiler (ref
python/paddle/fluid/compiler.py).

The compile-plan surface (PR 10): ``CompilePlan`` describes how a
(program, strategy) pair lowers — trace -> cut -> schedule -> jit — and
``BuildStrategy(pp_stages=K, pp_micro_batches=M, pp_schedule=...)``
selects the pipeline lowering (GPipe/1F1B over a "pp" mesh axis).
"""
from .framework.compiler import CompiledProgram, BuildStrategy, \
    ExecutionStrategy, CompilePlan  # noqa: F401

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy",
           "CompilePlan"]
