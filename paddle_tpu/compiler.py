"""Module-path alias for fluid.compiler (ref
python/paddle/fluid/compiler.py)."""
from .framework.compiler import CompiledProgram, BuildStrategy, \
    ExecutionStrategy  # noqa: F401

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]
