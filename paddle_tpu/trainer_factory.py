"""Trainer / device-worker equivalents.

Reference parity: python/paddle/fluid/trainer_factory.py +
device_worker.py (MultiTrainer + HogwildWorker, section_worker etc.). The
reference spins C++ worker threads each running the op list over a data
queue. On TPU the jitted step IS the worker — XLA dispatch is host-async,
so one Python thread keeps the chip busy while a background prefetch
thread (the DataFeed queue equivalent) collates the next batch and ships
it to HBM. Pipeline (section) scheduling lives in distributed/pipeline.py.
"""
import queue
import threading

_STOP = object()


def _uniform_shapes(batches):
    """True when every batch has the same keys and per-key shapes (the
    static-shape requirement of a fused scan window)."""
    import numpy as np
    first = batches[0]
    keys = set(first)
    return all(set(b) == keys for b in batches[1:]) and all(
        np.shape(b[k]) == np.shape(first[k])
        for b in batches[1:] for k in keys)


class PrefetchIterator(object):
    """Background-thread batch pump: the device_worker's data queue.
    Wraps any iterable of feed dicts; keeps up to `capacity` batches
    staged ahead of the consumer. close() (or abandoning the iterator
    after an error) unblocks and retires the pump thread."""

    def __init__(self, iterable, capacity=4):
        self._q = queue.Queue(maxsize=capacity)
        self._err = None
        self._stop = threading.Event()

        def put(item):
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def pump():
            try:
                for item in iterable:
                    if not put(item):
                        return
            except BaseException as e:   # surfaced on the consumer side
                self._err = e
            finally:
                put(_STOP)

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()

    def close(self):
        """Stop the pump thread (safe to call any time)."""
        self._stop.set()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is _STOP:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


class DeviceWorker(object):
    """Base device worker (reference device_worker.py DeviceWorker)."""

    def __init__(self):
        self._program = None

    def _set_program(self, program):
        self._program = program


class Hogwild(DeviceWorker):
    """Hogwild worker: plain step loop. On TPU, 'lock-free multithread
    update' degenerates to async dispatch of one fused step — the chip,
    not host threads, provides the parallelism."""


class DownpourSGD(DeviceWorker):
    """Pserver-style sparse push/pull worker. TPU-native: sharded
    embedding tables + lazy-mode optimizers replace push/pull (see
    distributed/sharded_embedding.py); the step loop is identical."""


class Section(DeviceWorker):
    """Pipeline section worker — superseded by the SPMD GPipe/1F1B
    schedules in distributed/pipeline.py."""


class TrainerDesc(object):
    def __init__(self):
        self._worker = Hogwild()
        self._fetch_vars = []
        self._fetch_info = []
        self._print_period = 100


class MultiTrainer(object):
    """Runs the jitted step over a prefetched dataset (reference
    MultiTrainer's thread pool collapses to prefetch + async dispatch)."""

    def __init__(self, executor, program, worker=None):
        self._exe = executor
        self._program = program
        self._worker = worker or Hogwild()
        self._worker._set_program(program)

    def run(self, dataset, fetch_list=None, fetch_info=None,
            print_period=100, debug=False, scope=None,
            steps_per_dispatch=1):
        import numpy as np
        fetch_list = list(fetch_list or [])
        fetch_info = list(fetch_info or
                          [getattr(f, "name", str(f)) for f in fetch_list])
        step = 0
        last = []
        # steps_per_dispatch > 1: gather W batches and run them as ONE
        # fused device program (Executor.run_steps lax.scan window) —
        # host/link dispatch latency amortizes W-fold. Needs fetches (the
        # scan's per-step outputs) and a plain Program; short tails fall
        # back to the per-step loop below.
        window = max(int(steps_per_dispatch), 1)
        if window > 1 and not self._can_window(fetch_list):
            window = 1
        buf = []

        def emit(vals, every_multiple=False):
            due = (step % print_period == 0 if every_multiple
                   else step % print_period < window)
            if debug and fetch_list and due:
                print("step %d: %s" % (step, ", ".join(
                    "%s=%s" % (info, np.asarray(v).ravel()[:4])
                    for info, v in zip(fetch_info, vals))))

        def run_one(batch):
            nonlocal step, last
            last = self._exe.run(self._program, feed=batch,
                                 fetch_list=fetch_list, scope=scope)
            step += 1
            # formatting syncs the async fetch values — the only
            # host/device sync point in the loop
            emit(last, every_multiple=True)

        it = PrefetchIterator(iter(dataset))
        try:
            for batch in it:
                if window == 1:
                    run_one(batch)
                    continue
                buf.append(batch)
                if len(buf) < window:
                    continue
                if not _uniform_shapes(buf):
                    # ragged window (bucketed lengths, remainder batch):
                    # a scan needs one static shape — run these per-step
                    for b in buf:
                        run_one(b)
                    buf = []
                    continue
                stacked = {k: np.stack([np.asarray(b[k]) for b in buf])
                           for k in buf[0]}
                buf = []
                outs = self._exe.run_steps(
                    self._program, feed=stacked,
                    fetch_list=fetch_list, scope=scope)
                step += window
                last = [o[-1] for o in outs]
                emit(last)
            for batch in buf:      # tail shorter than the window
                run_one(batch)
        finally:
            it.close()
        return step, last

    def _can_window(self, fetch_list):
        """run_steps preconditions — anything else silently degrades to
        the per-step loop instead of crashing mid-epoch. (CompiledProgram
        is fine: run_steps shards the scan over its mesh; pipeline
        programs window through Executor._run_pipeline_steps.)"""
        from paddle_tpu.framework.compiler import CompiledProgram
        prog = self._program
        if isinstance(prog, CompiledProgram):
            prog = prog._program
        return bool(fetch_list) \
            and not any(r._started for r in
                        getattr(prog, "_py_readers", ()))


class DistMultiTrainer(MultiTrainer):
    """Distributed variant: same loop; the mesh/collectives inside the
    compiled step (CompiledProgram shardings) replace the reference's
    trainer-side communicator."""


class TrainerFactory(object):
    def _create_trainer(self, opt_info=None):
        if opt_info and opt_info.get("trainer") == "DistMultiTrainer":
            return DistMultiTrainer
        return MultiTrainer
