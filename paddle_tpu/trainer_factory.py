"""Trainer / device-worker equivalents.

Reference parity: python/paddle/fluid/trainer_factory.py +
device_worker.py (MultiTrainer + HogwildWorker, section_worker etc.). The
reference spins C++ worker threads each running the op list over a data
queue. On TPU the jitted step IS the worker — XLA dispatch is host-async,
so one Python thread keeps the chip busy while a background prefetch
thread (the DataFeed queue equivalent) collates the next batch and ships
it to HBM. Pipeline (section) scheduling lives in distributed/pipeline.py.
"""
import queue
import threading

_STOP = object()


class PrefetchIterator(object):
    """Background-thread batch pump: the device_worker's data queue.
    Wraps any iterable of feed dicts; keeps up to `capacity` batches
    staged ahead of the consumer. close() (or abandoning the iterator
    after an error) unblocks and retires the pump thread."""

    def __init__(self, iterable, capacity=4):
        self._q = queue.Queue(maxsize=capacity)
        self._err = None
        self._stop = threading.Event()

        def put(item):
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def pump():
            try:
                for item in iterable:
                    if not put(item):
                        return
            except BaseException as e:   # surfaced on the consumer side
                self._err = e
            finally:
                put(_STOP)

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()

    def close(self):
        """Stop the pump thread (safe to call any time)."""
        self._stop.set()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is _STOP:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


class DeviceWorker(object):
    """Base device worker (reference device_worker.py DeviceWorker)."""

    def __init__(self):
        self._program = None

    def _set_program(self, program):
        self._program = program


class Hogwild(DeviceWorker):
    """Hogwild worker: plain step loop. On TPU, 'lock-free multithread
    update' degenerates to async dispatch of one fused step — the chip,
    not host threads, provides the parallelism."""


class DownpourSGD(DeviceWorker):
    """Pserver-style sparse push/pull worker. TPU-native: sharded
    embedding tables + lazy-mode optimizers replace push/pull (see
    distributed/sharded_embedding.py); the step loop is identical."""


class Section(DeviceWorker):
    """Pipeline section worker — superseded by the SPMD GPipe/1F1B
    schedules in distributed/pipeline.py."""


class TrainerDesc(object):
    def __init__(self):
        self._worker = Hogwild()
        self._fetch_vars = []
        self._fetch_info = []
        self._print_period = 100


class MultiTrainer(object):
    """Runs the jitted step over a prefetched dataset (reference
    MultiTrainer's thread pool collapses to prefetch + async dispatch)."""

    def __init__(self, executor, program, worker=None):
        self._exe = executor
        self._program = program
        self._worker = worker or Hogwild()
        self._worker._set_program(program)

    def run(self, dataset, fetch_list=None, fetch_info=None,
            print_period=100, debug=False, scope=None):
        import numpy as np
        fetch_list = list(fetch_list or [])
        fetch_info = list(fetch_info or
                          [getattr(f, "name", str(f)) for f in fetch_list])
        step = 0
        last = []
        it = PrefetchIterator(iter(dataset))
        try:
            for batch in it:
                last = self._exe.run(self._program, feed=batch,
                                     fetch_list=fetch_list, scope=scope)
                step += 1
                if debug and fetch_list and step % print_period == 0:
                    # formatting syncs the async fetch values — the only
                    # host/device sync point in the loop
                    print("step %d: %s" % (step, ", ".join(
                        "%s=%s" % (info, np.asarray(v).ravel()[:4])
                        for info, v in zip(fetch_info, last))))
        finally:
            it.close()
        return step, last


class DistMultiTrainer(MultiTrainer):
    """Distributed variant: same loop; the mesh/collectives inside the
    compiled step (CompiledProgram shardings) replace the reference's
    trainer-side communicator."""


class TrainerFactory(object):
    def _create_trainer(self, opt_info=None):
        if opt_info and opt_info.get("trainer") == "DistMultiTrainer":
            return DistMultiTrainer
        return MultiTrainer
