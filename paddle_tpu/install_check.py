"""Install sanity check.

Reference parity: python/paddle/fluid/install_check.py — builds a tiny
model, runs one train step, verifies the stack end-to-end.
"""
import numpy as np


def run_check():
    from . import (Program, program_guard, Executor, layers, optimizer,
                   global_scope)
    from .framework.scope import Scope, scope_guard
    main, startup = Program(), Program()
    with scope_guard(Scope()):
        with program_guard(main, startup):
            x = layers.data("install_check_x", [2], dtype="float32")
            y = layers.data("install_check_y", [1], dtype="float32")
            pred = layers.fc(x, size=1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            optimizer.SGD(0.01).minimize(loss)
        exe = Executor()
        exe.run(startup)
        out = exe.run(main,
                      feed={"install_check_x":
                            np.random.rand(4, 2).astype(np.float32),
                            "install_check_y":
                            np.random.rand(4, 1).astype(np.float32)},
                      fetch_list=[loss.name])
    assert np.isfinite(out[0]).all(), "install check produced non-finite loss"
    print("Your paddle_tpu works well on this device!")
    return True
