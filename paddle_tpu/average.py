"""WeightedAverage (reference: python/paddle/fluid/average.py)."""
import numpy as np


def _is_number_or_matrix(var):
    return isinstance(var, (int, float, complex, np.ndarray)) or \
        np.isscalar(var)


class WeightedAverage(object):
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError("add() expects a number or ndarray")
        value = np.mean(np.asarray(value, dtype=np.float64))
        if self.numerator is None:
            self.numerator = value * weight
            self.denominator = float(weight)
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator == 0:
            raise ValueError("WeightedAverage.eval() before any add()")
        return self.numerator / self.denominator
