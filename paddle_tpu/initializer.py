"""Parameter initializers.

Reference parity: python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArray). Each emits an
init op into the STARTUP program, exactly like the reference; the Executor
runs startup eagerly once and parameters live in Scope/HBM thereafter.
"""
import math

import numpy as np


class Initializer(object):
    def __call__(self, param, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, param, block):
        block.append_op(
            "fill_constant", outputs={"Out": [param.name]},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "value": float(self.value), "op_role": "init"})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, param, block):
        block.append_op(
            "uniform_random", outputs={"Out": [param.name]},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "min": self.low, "max": self.high, "seed": self.seed,
                   "op_role": "init"})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, param, block):
        block.append_op(
            "gaussian_random", outputs={"Out": [param.name]},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed,
                   "op_role": "init"})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, param, block):
        block.append_op(
            "truncated_gaussian_random", outputs={"Out": [param.name]},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed,
                   "op_role": "init"})


def _fans(shape):
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) > 2:
        rf = int(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * rf, shape[0] * rf
    else:
        fan_in = fan_out = int(np.prod(shape))
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, param, block):
        fi, fo = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(param, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(param, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, param, block):
        fi, _ = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(param, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(param, block)


class BilinearInitializer(Initializer):
    """For conv-transpose upsampling kernels (reference initializer.py)."""

    def __call__(self, param, block):
        shape = param.shape
        if len(shape) != 4:
            raise ValueError("bilinear init needs a 4-D conv weight")
        c_out, c_in, h, w = shape
        f = math.ceil(w / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        og = np.ogrid[:h, :w]
        filt = (1 - abs(og[0] / f - c)) * (1 - abs(og[1] / f - c))
        weight[range(c_out), range(c_in) if c_in == c_out else 0, :, :] = filt
        NumpyArrayInitializer(weight)(param, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, param, block):
        block.append_op(
            "assign_value", outputs={"Out": [param.name]},
            attrs={"shape": list(self.value.shape), "dtype": param.dtype,
                   "values": self.value.reshape(-1).tolist(),
                   "op_role": "init"})


# fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
NumpyArray = NumpyArrayInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)


import contextlib as _contextlib

_force_cpu_init = [False]


def force_init_on_cpu():
    """ref initializer.py force_init_on_cpu — whether the init_on_cpu
    guard is active.  On TPU initializers run inside the jitted startup
    step; the flag is tracked for parity and ignored by design (there
    is no separate CPU init path to route to)."""
    return _force_cpu_init[0]


@_contextlib.contextmanager
def init_on_cpu():
    """ref initializer.py init_on_cpu context guard (parity no-op on
    TPU; see force_init_on_cpu)."""
    _force_cpu_init[0] = True
    try:
        yield
    finally:
        _force_cpu_init[0] = False
