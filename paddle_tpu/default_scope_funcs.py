"""fluid.default_scope_funcs parity (ref
python/paddle/fluid/default_scope_funcs.py): thread-local stack of local
scopes over the global one."""
import threading

from .framework.scope import Scope, global_scope

__all__ = ["get_cur_scope", "enter_local_scope", "leave_local_scope",
           "var", "find_var", "scoped_function"]

_local = threading.local()


def _stack():
    if not hasattr(_local, "stack"):
        _local.stack = [global_scope()]
    return _local.stack


def get_cur_scope():
    return _stack()[-1]


def enter_local_scope():
    _stack().append(Scope())


def leave_local_scope():
    if len(_stack()) > 1:
        _stack().pop()


def var(name):
    return get_cur_scope().var(name)


def find_var(name):
    return get_cur_scope().find_var(name)


def scoped_function(func):
    enter_local_scope()
    try:
        func()
    finally:
        leave_local_scope()
