"""PaddlePS process-instance helper (ref fluid/distributed/
ps_instance.py): MPI-rank bookkeeping for pserver/trainer roles. TPU
jobs have one role (every host runs the same SPMD program under
jax.distributed), so the instance degenerates to process-index
accessors over the live runtime."""

__all__ = ["PaddlePSInstance"]


class PaddlePSInstance(object):
    def __init__(self, server_worker_mode=1, proc_per_node=1):
        import jax
        self._rank = jax.process_index()
        self._nodes = jax.process_count()

    def get_worker_index(self):
        return self._rank

    def get_node_cnt(self):
        return self._nodes

    def is_worker(self):
        return True           # every TPU host is a worker

    def is_server(self):
        return False          # no pserver tier on TPU (PORTING.md)

    def is_first_worker(self):
        return self._rank == 0

    def barrier_all(self):
        if self._nodes > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("paddle_tpu_ps_barrier")
