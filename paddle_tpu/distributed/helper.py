"""MPI helper surface (ref fluid/distributed/helper.py). The reference
wrapped mpi4py for pserver jobs; multi-host coordination here is
jax.distributed (distributed/launch.py init_on_pod), so the helper
exposes the same small API over the live runtime."""

__all__ = ["MPIHelper"]


class MPIHelper(object):
    def get_rank(self):
        import jax
        return jax.process_index()

    def get_size(self):
        import jax
        return jax.process_count()

    def get_ip(self):
        import socket
        return socket.gethostbyname(socket.gethostname())

    def get_hostname(self):
        import socket
        return socket.gethostname()
