"""Ring attention — sequence/context parallelism over the mesh.

First-class long-context support: the sequence axis is sharded over mesh
axis "sp"; each device holds a Q/K/V shard and K/V blocks rotate around the
ring via lax.ppermute while partial softmax statistics accumulate in
log-sum-exp form (online softmax). Communication rides ICI neighbor links —
bandwidth-optimal, memory O(T/n) per chip, exact (not approximate) attention.

No reference counterpart (the reference caps at single-device attention);
this is the capability the north star demands for pod-scale long sequences.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map_mod
    shard_map = _shard_map_mod
except Exception:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def _ring_attention_local(q, k, v, axis_name, causal, scale, q_offset_blocks):
    """Per-shard body. q,k,v: (B, H, Tl, D) local shards."""
    n = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, tl, d = q.shape

    # online softmax accumulators (pvary: mark as device-varying for the
    # shard_map carry type system)
    def _vary(x):
        try:
            return lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError):  # older jax spellings
            try:
                return lax.pvary(x, (axis_name,))
            except AttributeError:
                return x
    acc = _vary(jnp.zeros((b, h, tl, d), jnp.float32))
    row_max = _vary(jnp.full((b, h, tl), -jnp.inf, jnp.float32))
    row_sum = _vary(jnp.zeros((b, h, tl), jnp.float32))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def block(carry, step):
        acc, row_max, row_sum, kk, vv = carry
        kv_idx = (my_idx - step) % n
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = my_idx * tl + jnp.arange(tl)
            k_pos = kv_idx * tl + jnp.arange(tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -1e30)
        blk_max = jnp.max(logits, axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(logits - new_max[..., None])
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vv.astype(jnp.float32))
        row_sum = row_sum * correction + jnp.sum(p, axis=-1)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (acc, new_max, row_sum, kk, vv), None

    (acc, row_max, row_sum, _, _), _ = lax.scan(
        block, (acc, row_max, row_sum, k, v), jnp.arange(n))
    out = acc / jnp.maximum(row_sum[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis_name="sp", causal=False,
                   scale=None):
    """q,k,v: (B, H, T, D) arrays (or sharded jax.Arrays); T sharded on
    `axis_name`. Returns attention output with the same sharding."""
    from .mesh import get_mesh
    mesh = mesh or get_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        raise ValueError("ring_attention needs a mesh with axis %r"
                         % axis_name)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale, q_offset_blocks=0),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
