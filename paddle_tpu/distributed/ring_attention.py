"""Ring attention — sequence/context parallelism over the mesh.

First-class long-context support: the sequence axis is sharded over mesh
axis "sp"; each device holds a Q/K/V shard and K/V blocks rotate around the
ring via lax.ppermute while partial softmax statistics accumulate in
log-sum-exp form (online softmax). Communication rides ICI neighbor links —
bandwidth-optimal, memory O(T/n) per chip, exact (not approximate) attention.

No reference counterpart (the reference caps at single-device attention);
this is the capability the north star demands for pod-scale long sequences.
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map_mod
    shard_map = _shard_map_mod
except Exception:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


from .pipeline import _pvary as _vary  # shared pcast/pvary compat shim


def _ring_perm(n):
    """Neighbor rotation i -> i+1; backward MUST replay the forward's exact
    rotation order (both sides call this one factory)."""
    return [(i, (i + 1) % n) for i in range(n)]


# canonical jax-version compat shim (0.4.x has no lax.axis_size) lives
# beside the collective kernels; ops never imports distributed at module
# level, so this direction is cycle-free
from ..ops.collective_ops import _axis_size  # noqa: E402


def _block_logits(q, kk, my_idx, kv_idx, scale, causal, mm=None):
    """Scaled (and causally masked) logits of the local Q shard against a
    visiting K block. `mm` is the visiting ADDITIVE key-padding mask block
    (..., 1, Tk_block) riding the ring with its K/V block."""
    tl = q.shape[2]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = my_idx * tl + jnp.arange(tl)
        k_pos = kv_idx * tl + jnp.arange(tl)
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None], logits, -1e30)
    if mm is not None:
        logits = logits + mm.astype(jnp.float32)
    return logits


def _ring_forward(q, k, v, axis_name, causal, scale, mask=None):
    """Online-softmax ring pass. Returns (out, lse) where lse is the
    per-row log-sum-exp — the only statistic backward needs. `mask` is
    this shard's additive key-padding block (..., 1, Tk_local); it rides
    the ring with its K/V block."""
    n = _axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, tl, d = q.shape

    acc = _vary(jnp.zeros((b, h, tl, d), jnp.float32), axis_name)
    row_max = _vary(jnp.full((b, h, tl), -jnp.inf, jnp.float32), axis_name)
    row_sum = _vary(jnp.zeros((b, h, tl), jnp.float32), axis_name)

    perm = _ring_perm(n)
    has_mask = mask is not None

    def block(carry, step):
        if has_mask:
            acc, row_max, row_sum, kk, vv, mm = carry
        else:
            acc, row_max, row_sum, kk, vv = carry
            mm = None
        kv_idx = (my_idx - step) % n
        logits = _block_logits(q, kk, my_idx, kv_idx, scale, causal, mm)
        blk_max = jnp.max(logits, axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(logits - new_max[..., None])
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vv.astype(jnp.float32))
        row_sum = row_sum * correction + jnp.sum(p, axis=-1)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        if has_mask:
            mm = lax.ppermute(mm, axis_name, perm)
            return (acc, new_max, row_sum, kk, vv, mm), None
        return (acc, new_max, row_sum, kk, vv), None

    carry0 = (acc, row_max, row_sum, k, v)
    if has_mask:
        carry0 = carry0 + (mask,)
    carry, _ = lax.scan(block, carry0, jnp.arange(n))
    acc, row_max, row_sum = carry[0], carry[1], carry[2]
    safe_sum = jnp.maximum(row_sum, 1e-30)
    out = acc / safe_sum[..., None]
    lse = row_max + jnp.log(safe_sum)
    return out.astype(q.dtype), lse


def _make_local(axis_name, causal, scale):
    """Per-shard ring attention with a custom vjp that REPLAYS the ring in
    backward (flash-attention-style recompute): residuals are only
    (q, k, v, out, lse) — O(T/n) per chip — never the n visiting K/V
    blocks a plain autodiff-through-scan would stash. dK/dV accumulators
    rotate around the ring in lockstep with their K/V blocks and arrive
    home after n hops with every device's contribution."""

    def _bwd_ring(q, k, v, mask, out, lse, dout):
        """Shared ring-replay backward; mask (or None) rides the ring in
        lockstep with its K/V block exactly as in forward."""
        n = _axis_size(axis_name)
        my_idx = lax.axis_index(axis_name)
        dout32 = dout.astype(jnp.float32)
        # delta_i = sum_j dOut_ij * Out_ij (standard flash backward term)
        delta = jnp.sum(dout32 * out.astype(jnp.float32), axis=-1)
        dq0 = _vary(jnp.zeros(q.shape, jnp.float32), axis_name)
        dk0 = _vary(jnp.zeros(k.shape, jnp.float32), axis_name)
        dv0 = _vary(jnp.zeros(v.shape, jnp.float32), axis_name)
        perm = _ring_perm(n)
        has_mask = mask is not None

        def block(carry, step):
            if has_mask:
                dq, kk, vv, dkk, dvv, mm = carry
            else:
                dq, kk, vv, dkk, dvv = carry
                mm = None
            kv_idx = (my_idx - step) % n
            logits = _block_logits(q, kk, my_idx, kv_idx, scale, causal,
                                   mm)
            p = jnp.exp(logits - lse[..., None])      # (B,H,Tq,Tk)
            dvv = dvv + jnp.einsum("bhqk,bhqd->bhkd", p, dout32)
            dp = jnp.einsum("bhqd,bhkd->bhqk", dout32,
                            vv.astype(jnp.float32))
            ds = p * (dp - delta[..., None]) * scale
            dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds,
                                 kk.astype(jnp.float32))
            dkk = dkk + jnp.einsum("bhqk,bhqd->bhkd", ds,
                                   q.astype(jnp.float32))
            kk = lax.ppermute(kk, axis_name, perm)
            vv = lax.ppermute(vv, axis_name, perm)
            dkk = lax.ppermute(dkk, axis_name, perm)
            dvv = lax.ppermute(dvv, axis_name, perm)
            if has_mask:
                mm = lax.ppermute(mm, axis_name, perm)
                return (dq, kk, vv, dkk, dvv, mm), None
            return (dq, kk, vv, dkk, dvv), None

        carry0 = (dq0, k, v, dk0, dv0)
        if has_mask:
            carry0 = carry0 + (mask,)
        carry, _ = lax.scan(block, carry0, jnp.arange(n))
        dq, dk, dv = carry[0], carry[3], carry[4]
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    @jax.custom_vjp
    def attn(q, k, v):
        out, _ = _ring_forward(q, k, v, axis_name, causal, scale)
        return out

    def fwd(q, k, v):
        out, lse = _ring_forward(q, k, v, axis_name, causal, scale)
        return out, (q, k, v, out, lse)

    def bwd(res, dout):
        q, k, v, out, lse = res
        return _bwd_ring(q, k, v, None, out, lse, dout)

    attn.defvjp(fwd, bwd)

    @jax.custom_vjp
    def attn_masked(q, k, v, mask):
        out, _ = _ring_forward(q, k, v, axis_name, causal, scale, mask)
        return out

    def fwd_m(q, k, v, mask):
        out, lse = _ring_forward(q, k, v, axis_name, causal, scale, mask)
        return out, (q, k, v, mask, out, lse)

    def bwd_m(res, dout):
        q, k, v, mask, out, lse = res
        dq, dk, dv = _bwd_ring(q, k, v, mask, out, lse, dout)
        # additive key-padding masks come from stop_gradient feeds; a
        # symbolic-zero cotangent keeps the vjp total
        return dq, dk, dv, jnp.zeros_like(mask)

    attn_masked.defvjp(fwd_m, bwd_m)
    return attn, attn_masked


def ring_attention(q, k, v, mask=None, mesh=None, axis_name="sp",
                   causal=False, scale=None):
    """q,k,v: (B, H, T, D) arrays (or sharded jax.Arrays); T sharded on
    `axis_name`. `mask` is an optional ADDITIVE key-padding mask
    broadcastable as (..., 1, T) — e.g. BERT's (B, 1, 1, T) attn bias;
    its key axis is sharded over the ring and each block travels with
    its K/V block. Per-query masks (Tq > 1 in dim -2) can't ride the
    ring (the query shard stays home) — use ulysses_attention for those.
    Returns attention output with the same sharding as q."""
    from .mesh import get_mesh
    mesh = mesh or get_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        raise ValueError("ring_attention needs a mesh with axis %r"
                         % axis_name)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, None, axis_name, None)
    attn, attn_masked = _make_local(axis_name, causal, scale)
    if mask is None:
        fn = shard_map(attn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
        return fn(q, k, v)
    if mask.ndim < 2 or mask.shape[-2] != 1:
        raise ValueError(
            "ring_attention mask must be a key-padding mask broadcastable "
            "as (..., 1, T); got shape %r — per-query masks need "
            "ulysses_attention" % (tuple(mask.shape),))
    mspec = P(*([None] * (mask.ndim - 1) + [axis_name]))
    fn = shard_map(attn_masked, mesh=mesh,
                   in_specs=(spec, spec, spec, mspec), out_specs=spec)
    return fn(q, k, v, mask)
