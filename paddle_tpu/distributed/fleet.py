"""Fleet-style distributed training API.

Reference parity: python/paddle/fluid/incubate/fleet/ (collective mode) +
transpiler/distribute_transpiler.py. The reference rewrites programs into
pserver/trainer pairs or inserts NCCL allreduce; TPU-native fleet simply
(1) installs a mesh, (2) annotates parameter shardings per strategy, and
(3) hands the program to CompiledProgram/pjit — XLA does the communication.
"""
import jax

from . import mesh as mesh_mod

_role = {"initialized": False}


class PaddleCloudRoleMaker(object):
    """Multi-host role discovery (reference role_maker.py). Under JAX each
    host runs the same program; rank/size come from jax.distributed."""

    def __init__(self, is_collective=True):
        self.is_collective = is_collective

    def worker_index(self):
        return jax.process_index()

    def worker_num(self):
        return jax.process_count()

    def is_first_worker(self):
        return jax.process_index() == 0


def init(role_maker=None, is_collective=True, strategy=None):
    _role["initialized"] = True
    _role["role_maker"] = role_maker or PaddleCloudRoleMaker(is_collective)
    strategy = strategy or mesh_mod.DistributedStrategy()
    _role["strategy"] = strategy
    if mesh_mod.get_mesh() is None:
        mesh_mod.init_mesh(strategy.mesh_axes)
    return _role["role_maker"]


def worker_index():
    return _role["role_maker"].worker_index() if _role.get("role_maker") \
        else 0


def worker_num():
    return _role["role_maker"].worker_num() if _role.get("role_maker") else 1


def is_first_worker():
    return worker_index() == 0


class DistributedOptimizer(object):
    def __init__(self, optimizer, strategy=None):
        self._inner = optimizer
        self._strategy = strategy or _role.get(
            "strategy", mesh_mod.DistributedStrategy())

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if getattr(self._strategy, "pipeline", False):
            return self._minimize_pipeline(loss)
        ops, pgs = self._inner.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)
        # ZeRO-1: annotate optimizer moments for dp sharding when
        # requested.  The annotation is nominal — CompiledProgram's
        # _var_sharding checks it against the REAL mesh at compile time
        # and keeps non-divisible dims (e.g. a 4-wide bias moment on
        # dp=8) replicated.
        if self._strategy.sharding_optimizer_state:
            for (name, pname), var in getattr(self._inner, "_accumulators",
                                              {}).items():
                if var.shape and var.shape[0] > 1:
                    var.sharding = ("dp",) + (None,) * (len(var.shape) - 1)
        return ops, pgs


    def _minimize_pipeline(self, loss):
        """Pipeline mode (ref fluid PipelineOptimizer): instead of
        appending backward+update ops, partition the stage-stamped Program
        (pipeline_program.extract_pipeline_plan) and install the plan +
        optimizer on it; Executor.run then executes the GPipe/1F1B
        shard_map schedule and the functional update twin of the inner
        optimizer, all in one jitted step."""
        from . import pipeline_program as ppp
        strategy = self._strategy
        program = loss.block.program
        plan = ppp.extract_pipeline_plan(
            program, loss.name,
            schedule=getattr(strategy, "pp_schedule", "1f1b"),
            n_micro=getattr(strategy, "pp_num_micro", 1))
        # fail fast on unsupported optimizers, at minimize time not run time
        ppp.make_update_fn(self._inner)
        program._pp_plan = plan
        program._pp_optimizer = self._inner
        # a re-minimize must not reuse a step/optimizer-state compiled for
        # the previous plan/optimizer
        program._pp_step_cache = {}
        program._pp_opt_state = None
        program._version += 1
        return [], []


def distributed_optimizer(optimizer, strategy=None):
    return DistributedOptimizer(optimizer, strategy)


def main_program_compiled(loss_program=None):
    """Build the CompiledProgram for the installed mesh."""
    from ..framework.program import default_main_program
    from ..framework.compiler import CompiledProgram, BuildStrategy
    program = loss_program or default_main_program()
    strategy = _role.get("strategy", mesh_mod.DistributedStrategy())
    bs = BuildStrategy()
    bs.mesh_axes = dict(strategy.mesh_axes)
    bs.collective_timeout_s = getattr(strategy, "collective_timeout_s", None)
    return CompiledProgram(program, bs)
