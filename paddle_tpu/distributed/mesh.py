"""Device-mesh management.

Reference parity: the reference builds NCCL communicators per ring
(c_comm_init / gen_nccl_id over brpc); TPU-native: a single jax.sharding.Mesh
over all devices. Axes convention:

  dp — data parallel (batch)          mp — tensor/model parallel
  pp — pipeline stages                sp — sequence/context parallel

Multi-host: jax.distributed.initialize() enrolls every host in the same
mesh; XLA routes collectives over ICI within a pod slice and DCN across
slices — no parameter server processes needed.
"""
import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_mesh = None
_mesh_axes = None      # last init_mesh axes — what a re-init rebuilds from
_reinit_hooks = []     # fns(lost_hosts, live_hosts, mesh) run after re-init
_lost_hosts = set()    # hosts currently out of the mesh (cumulative)
_total_hosts = None    # pod size the loss/absorb fractions scale against


class DistributedStrategy(object):
    """Reference parity: fleet DistributedStrategy. Fields map reference
    knobs onto mesh/sharding decisions."""

    def __init__(self):
        self.mesh_axes = {"dp": 1}
        self.amp = False
        self.recompute = False
        self.gradient_merge_steps = 1
        self.sharding_optimizer_state = False  # ZeRO-1 style
        self.collective_timeout_s = 600.0
        # pipeline parallelism (fleet path; distributed/pipeline_program.py)
        self.pipeline = False
        self.pp_schedule = "1f1b"      # "1f1b" | "gpipe"
        self.pp_num_micro = 1


def init_mesh(mesh_axes=None, devices=None, multihost=False):
    """Create and install the global mesh. mesh_axes e.g. {"dp":2,"mp":4}."""
    global _mesh, _mesh_axes
    if multihost and jax.process_count() == 1:
        try:
            jax.distributed.initialize()
        except Exception:
            pass
    devices = devices if devices is not None else jax.devices()
    mesh_axes = mesh_axes or {"dp": len(devices)}
    sizes = list(mesh_axes.values())
    n = int(np.prod(sizes))
    dev = np.array(devices[:n]).reshape(sizes)
    _mesh = Mesh(dev, tuple(mesh_axes.keys()))
    _mesh_axes = dict(mesh_axes)
    return _mesh


def reset_mesh():
    """Uninstall the global mesh (tests / reconfiguration)."""
    global _mesh, _mesh_axes, _total_hosts
    _mesh = None
    _mesh_axes = None
    _lost_hosts.clear()
    _total_hosts = None


def add_reinit_hook(fn):
    """Register ``fn(lost_hosts, live_hosts, mesh)`` to run after the
    mesh is rebuilt on a host loss (recompile caches, re-place state,
    notify data loaders). Returns fn for decorator use."""
    _reinit_hooks.append(fn)
    return fn


def clear_reinit_hooks():
    del _reinit_hooks[:]


def handle_host_loss(lost_hosts, live_hosts):
    """Coordinator host-loss hook: rebuild the global mesh over the
    surviving topology and fan out to :func:`add_reinit_hook` hooks.

    The reference restarts NCCL rings (gen_nccl_id + c_comm_init) when a
    trainer drops; the XLA equivalent is re-making the Mesh so the next
    jit re-partitions over the survivors. Data-parallel capacity shrinks
    with the hosts, so the ``dp`` axis is scaled by the survivor
    fraction (model axes describe the MODEL — they must survive intact
    or the job cannot run at all and a NoQuorum/cold-start escalation is
    the right move). On a real pod, jax.distributed re-initialization
    (coordinator-led) replaces the device list; in the single-process
    simulation the visible devices are unchanged and only the shape
    scales. Returns the new mesh (or None when none was installed)."""
    global _mesh, _mesh_axes, _total_hosts
    from ..framework import resilience
    lost, live = sorted(lost_hosts), sorted(live_hosts)
    _lost_hosts.clear()
    _lost_hosts.update(lost)
    _total_hosts = len(lost) + len(live)
    resilience.record_event("mesh_reinit", lost=lost, live=live)
    if _mesh is not None and _mesh_axes:
        # scale from the ORIGINAL axes: lost_hosts is cumulative, so a
        # second loss must not compound a shrink already applied
        base = dict(_mesh_axes)
        axes = dict(base)
        total = len(lost) + len(live)
        if lost and total and "dp" in axes and axes["dp"] > 1:
            axes["dp"] = max(1, axes["dp"] * len(live) // total)
        init_mesh(axes)
        _mesh_axes = base
    for fn in list(_reinit_hooks):
        fn(lost, live, _mesh)
    return _mesh


def absorb_hosts(joined, live_hosts):
    """Inverse of :func:`handle_host_loss`: hosts rejoined the pod —
    re-grow the mesh over the restored topology and fan out to the same
    :func:`add_reinit_hook` hooks (state must be re-sharded back onto
    the larger mesh, step functions recompiled, loaders re-balanced).

    ``joined`` are the hosts being re-absorbed; ``live_hosts`` is the
    live set INCLUDING them. The axes scale from the ORIGINAL topology
    by the new live fraction — when every host is back, the mesh is
    bitwise the full one again, so an Executor/compiler cache keyed on
    the axes (CompiledProgram._cache_token) re-uses the pre-shrink
    executables. Returns the new mesh (or None when none is installed).
    """
    global _mesh, _mesh_axes, _total_hosts
    from ..framework import resilience
    joined, live = sorted(joined), sorted(live_hosts)
    _lost_hosts.difference_update(joined)
    if _total_hosts is None:
        _total_hosts = len(_lost_hosts) + len(live)
    total = _total_hosts
    resilience.record_event("mesh_absorb", joined=joined, live=live,
                            capacity="%d/%d" % (len(live), total))
    if _mesh is not None and _mesh_axes:
        base = dict(_mesh_axes)
        axes = dict(base)
        if _lost_hosts and total and "dp" in axes and axes["dp"] > 1:
            axes["dp"] = max(1, axes["dp"] * len(live) // total)
        init_mesh(axes)
        _mesh_axes = base
    for fn in list(_reinit_hooks):
        fn(sorted(_lost_hosts), live, _mesh)
    return _mesh


def _remap_spec(spec, new_mesh, shape):
    """Filter a PartitionSpec for ``new_mesh``: drop axes the mesh does
    not have and axes whose dim no longer divides the (resized) mesh
    axis — those dims fall back to replicated, mirroring
    CompiledProgram._var_sharding's divisibility rule."""
    axes = set(new_mesh.axis_names)
    out = []
    for i, entry in enumerate(tuple(spec)):
        names = entry if isinstance(entry, tuple) else (entry,)
        keep = []
        for a in names:
            if a is None or a not in axes:
                continue
            keep.append(a)
        if not keep:
            out.append(None)
            continue
        factor = int(np.prod([new_mesh.shape[a] for a in keep]))
        if i < len(shape) and shape[i] is not None \
                and shape[i] % factor != 0:
            out.append(None)
        elif len(keep) == 1:
            out.append(keep[0])
        else:
            out.append(tuple(keep))
    return P(*out)


def reshard_state(state, old_mesh, new_mesh):
    """Remap every NamedSharding-annotated leaf of ``state`` (a
    ``{name: array}`` mapping — e.g. ``dict(scope.items())``) from
    ``old_mesh`` onto ``new_mesh``. Returns a new dict; non-device and
    already-resident leaves pass through untouched.

    The common case — a ``dp`` axis resize where every dim still
    divides — is ONE sharded ``jax.device_put`` per leaf (XLA moves
    only the bytes that change owner). Anything device_put cannot
    express (changed device sets across processes, exotic layouts)
    falls back to gather-then-reshard: materialize on host, then place
    with the new sharding. Specs are filtered per ``new_mesh`` exactly
    like CompiledProgram._var_sharding (missing axes and non-dividing
    dims go replicated), so a shrunk mesh never produces an invalid
    NamedSharding."""
    from ..framework import resilience
    out, moved, gathered = {}, 0, 0
    for name, val in state.items():
        if not isinstance(val, jax.Array):
            out[name] = val
            continue
        sh = getattr(val, "sharding", None)
        if not isinstance(sh, NamedSharding):
            out[name] = val
            continue
        target = NamedSharding(new_mesh,
                               _remap_spec(sh.spec, new_mesh, val.shape))
        if sh == target:
            out[name] = val
            continue
        try:
            out[name] = jax.device_put(val, target)
            moved += 1
        except Exception:
            # gather-then-reshard: the general fallback when a direct
            # cross-sharding transfer is not expressible
            out[name] = jax.device_put(np.asarray(val), target)
            gathered += 1
    resilience.record_event(
        "reshard", moved=moved, gathered=gathered,
        old=None if old_mesh is None else
        {a: int(s) for a, s in old_mesh.shape.items()},
        new={a: int(s) for a, s in new_mesh.shape.items()})
    return out


def get_mesh():
    return _mesh


def mesh_axes():
    return tuple(_mesh.axis_names) if _mesh is not None else ()


def shard_parameter(param, spec):
    """Annotate a Parameter's sharding, e.g. shard_parameter(w, ("mp", None))."""
    param.sharding = tuple(spec)
    return param


def column_parallel_attr(name=None, **kw):
    """ParamAttr for a column-parallel fc weight (out-dim sharded on mp):
    matmul is local; XLA all-gathers activations only when needed."""
    from ..param_attr import ParamAttr
    return ParamAttr(name=name, sharding=(None, "mp"), **kw)


def row_parallel_attr(name=None, **kw):
    """ParamAttr for a row-parallel fc weight (in-dim sharded on mp);
    XLA inserts the psum on the output."""
    from ..param_attr import ParamAttr
    return ParamAttr(name=name, sharding=("mp", None), **kw)
