"""Downpour node descriptors (ref fluid/distributed/node.py): Server/
Worker table configs for the pserver binary. N/A on TPU — tables are
row-sharded mesh arrays (distributed/sharded_embedding.py); the classes
raise with that pointer so ported configs fail at the right line."""

__all__ = ["DownpourServer", "DownpourWorker"]

_GUIDANCE = (
    "Downpour server/worker table configs target the reference's "
    "pserver binary; on paddle_tpu use embedding(..., "
    "is_distributed=True) row-sharded tables (PORTING.md 'Capability "
    "substitutions')")


class DownpourServer(object):
    def __init__(self):
        raise NotImplementedError(_GUIDANCE)


class DownpourWorker(object):
    def __init__(self, window=1):
        raise NotImplementedError(_GUIDANCE)
