"""Distributed runtime: device meshes, fleet API, sequence parallelism.

Reference parity: operators/collective (NCCL), operators/distributed
(pserver), incubate/fleet, transpiler/distribute_transpiler.py. TPU-native
replacement: one jax.sharding.Mesh spanning all chips (ICI) / hosts (DCN),
sharding annotations instead of program transpilation, XLA collectives
instead of NCCL/brpc.
"""
from .mesh import (init_mesh, get_mesh, mesh_axes, DistributedStrategy,
                   shard_parameter, column_parallel_attr, row_parallel_attr)
from . import fleet
from . import launch
from .launch import init_on_pod
from .ring_attention import ring_attention
from .ulysses_attention import ulysses_attention
from .pipeline import (pipeline_forward, pipeline_loss_and_grads,
                       pipeline_1f1b_step, stack_stage_params)
from .sharded_embedding import (sharded_embedding_lookup, ShardedEmbedding,
                                distributed_embedding_attr)
