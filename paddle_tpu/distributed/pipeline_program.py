"""Static-Program pipeline partitioning — the fleet pp path.

Reference parity: fluid PipelineOptimizer + section_worker
(python/paddle/fluid/optimizer.py class PipelineOptimizer,
paddle/fluid/framework/device_worker.cc SectionWorker): the reference cuts
a Program into device-annotated "sections" run on different GPUs joined by
queues. TPU-native: ops are stamped with a ``pp_stage`` attr (via
``pp_stage_guard``, our device_guard), the stages are validated to be
structurally identical, and ONE stage callable is traced from the stage-0
template — the SPMD form distributed/pipeline.py's GPipe/1F1B schedules
need. fleet.distributed_optimizer wires this plan into Executor.run.

v1 contract (validated, with clear errors):
  feed x -> [stage 0 | stage 1 | ... | stage n-1] -> loss section(h, y)
  - every stage has the same op-type sequence and parameter shapes;
  - each stage consumes exactly one non-parameter activation;
  - the trailing (unstamped) loss section uses no parameters.
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp


@contextlib.contextmanager
def pp_stage_guard(stage, program=None):
    """Stamp every op appended inside with pp_stage=stage (device_guard
    equivalent; ref fluid.device_guard usage in pipeline models)."""
    from ..framework.program import default_main_program
    program = program if program is not None else default_main_program()
    old = getattr(program, "_pp_stage_ctx", None)
    program._pp_stage_ctx = int(stage)
    try:
        yield
    finally:
        program._pp_stage_ctx = old


class PipelinePlan(object):
    """Everything Executor needs to run a stage-partitioned Program."""

    __slots__ = ("n_stage", "template_ops", "tail_ops", "stage_params",
                 "template_params", "stage_in", "stage_out", "x_feed",
                 "y_feeds", "loss_name", "schedule", "n_micro")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


def _is_param(block, name):
    from ..framework.program import Parameter
    var = block._find_var_recursive(name)
    return isinstance(var, Parameter)


def _stage_signature(ops):
    """Structural signature for homogeneity checks: op types + attrs
    (minus the stage stamp) + slot arities."""
    sig = []
    for op in ops:
        attrs = {k: v for k, v in op.attrs.items() if k != "pp_stage"}
        sig.append((op.type, sorted((k, len(v)) for k, v in op.inputs.items()),
                    sorted((k, len(v)) for k, v in op.outputs.items()),
                    sorted((k, repr(v)) for k, v in attrs.items())))
    return sig


def _stage_io(block, ops):
    """(params, external_input, output) of one stage's op list."""
    produced = set()
    params, external = [], []
    for op in ops:
        for name in op.input_names():
            if name in produced or name in params or name in external:
                continue
            if _is_param(block, name):
                params.append(name)
            else:
                external.append(name)
        produced.update(op.output_names())
    if len(external) != 1:
        raise ValueError(
            "pipeline stage must consume exactly one activation; got "
            "external inputs %r (feed labels/aux inputs belong to the "
            "unstamped loss section)" % (external,))
    # stage output: last op's first output that leaves the stage is the
    # conventional chain var; use the final op's first output slot.
    out = ops[-1].output_names()[-1]
    return params, external[0], out


def extract_pipeline_plan(program, loss_name, schedule="1f1b", n_micro=1,
                          ops=None):
    """Partition `program` into the homogeneous-stage pipeline plan.

    ``ops`` restricts the partition to an explicit op list (the
    CompiledProgram path passes the FORWARD section of a minimized
    program; the fleet path leaves it None = every op in the block)."""
    blk = program.global_block()
    staged, tail, head = {}, [], []
    for op in (blk.ops if ops is None else ops):
        s = op.attrs.get("pp_stage")
        if s is None:
            (tail if staged else head).append(op)
        else:
            staged.setdefault(int(s), []).append(op)
    if not staged:
        raise ValueError("no ops stamped with pp_stage — build the model "
                         "inside pp_stage_guard(stage) sections")
    if head:
        raise ValueError(
            "ops before the first pipeline stage are not supported (v1): "
            "%r" % [op.type for op in head])
    n_stage = len(staged)
    if sorted(staged) != list(range(n_stage)):
        raise ValueError("pp_stage stamps must be contiguous 0..n-1; got %r"
                         % sorted(staged))
    template = staged[0]
    tsig = _stage_signature(template)
    for s in range(1, n_stage):
        if _stage_signature(staged[s]) != tsig:
            raise ValueError(
                "pipeline stages must be structurally identical (SPMD "
                "GPipe/1F1B contract); stage %d differs from stage 0" % s)
    per_stage_io = [_stage_io(blk, staged[s]) for s in range(n_stage)]
    template_params, stage_in, stage_out = per_stage_io[0]
    for s in range(n_stage):
        ps, _, _ = per_stage_io[s]
        for a, b in zip(template_params, ps):
            va, vb = blk._find_var_recursive(a), blk._find_var_recursive(b)
            if tuple(va.shape) != tuple(vb.shape):
                raise ValueError(
                    "stage %d param %s shape %s != stage 0 param %s shape "
                    "%s" % (s, b, vb.shape, a, va.shape))
    # chain check: stage s+1's input must be stage s's output
    for s in range(1, n_stage):
        if per_stage_io[s][1] != per_stage_io[s - 1][2]:
            raise ValueError(
                "stage %d consumes %r but stage %d produces %r — stages "
                "must chain" % (s, per_stage_io[s][1], s - 1,
                                per_stage_io[s - 1][2]))
    # tail: loss section (h, label/aux feeds...) -> loss
    staged_produced = set()
    for s in range(n_stage):
        for op in staged[s]:
            staged_produced.update(op.output_names())
    tail_params = set()
    produced = set()
    tail_external = []
    for op in tail:
        for name in op.input_names():
            if name in produced or name in tail_external:
                continue
            if _is_param(blk, name):
                tail_params.add(name)
            elif name != per_stage_io[-1][2]:
                if name in staged_produced:
                    # stage-internal activations stay sharded on the pp
                    # ring — the loss section may only read the chain
                    # output; catch it HERE with a named error instead of
                    # a KeyError at run time
                    raise ValueError(
                        "loss section reads %r, an activation produced "
                        "inside a pipeline stage — only the last stage's "
                        "chain output %r and data feeds may enter the "
                        "loss section" % (name, per_stage_io[-1][2]))
                tail_external.append(name)
        produced.update(op.output_names())
    if tail_params:
        raise ValueError("loss section with parameters is not supported "
                         "(v1): %r" % sorted(tail_params))
    if loss_name not in produced:
        raise ValueError("loss %r is not produced by the unstamped tail "
                         "section" % loss_name)
    return PipelinePlan(
        n_stage=n_stage, template_ops=template, tail_ops=tail,
        stage_params=[per_stage_io[s][0] for s in range(n_stage)],
        template_params=template_params, stage_in=stage_in,
        stage_out=per_stage_io[-1][2], x_feed=stage_in,
        y_feeds=list(tail_external), loss_name=loss_name,
        schedule=schedule, n_micro=int(n_micro))


def make_stage_fn(program, plan):
    """ONE SPMD stage callable traced from the stage-0 template:
    stage_fn({template_param_name: value}, h) -> h_next."""
    from ..framework.trace import TraceContext, trace_op

    def stage_fn(params_me, h):
        env = dict(params_me)
        env[plan.stage_in] = h
        ctx = TraceContext(program, jax.random.PRNGKey(program.random_seed))
        for i, op in enumerate(plan.template_ops):
            trace_op(op, env, ctx, rng_tag=7000003 + i)
        return env[plan.template_ops[-1].output_names()[-1]]

    return stage_fn


def make_loss_fn(program, plan):
    """loss_fn(h_last, ys) -> scalar, traced from the unstamped tail.
    `ys` is a tuple aligned with plan.y_feeds (any number of label/aux
    feeds the loss section consumes)."""
    tail_fn = make_tail_fn(program, plan, (plan.loss_name,))

    def loss_fn(h, ys):
        return tail_fn(h, ys)[0]

    return loss_fn


def make_tail_fn(program, plan, out_names):
    """tail_fn(h_last_full, ys_full) -> tuple of `out_names` values: the
    whole unstamped loss section traced on the UN-microbatched batch —
    how arbitrary fetch_list entries (metrics, logits, ...) are computed
    with exactly the serial program's semantics."""
    from ..framework.trace import TraceContext, trace_op

    def tail_fn(h, ys):
        env = {plan.stage_out: h}
        env.update(zip(plan.y_feeds, ys))
        ctx = TraceContext(program, jax.random.PRNGKey(program.random_seed))
        for i, op in enumerate(plan.tail_ops):
            trace_op(op, env, ctx, rng_tag=9000003 + i)
        return tuple(env[n] for n in out_names)

    return tail_fn


def stack_params_from_scope(plan, scope):
    """{template_param_name: (n_stage, ...) stacked values} from the
    per-stage scope entries."""
    stacked = {}
    for j, tname in enumerate(plan.template_params):
        vals = []
        for s in range(plan.n_stage):
            v = scope.find_var(plan.stage_params[s][j])
            if v is None:
                raise ValueError("pipeline param %r not initialized — run "
                                 "the startup program first"
                                 % plan.stage_params[s][j])
            vals.append(v)
        stacked[tname] = jnp.stack(vals)
    return stacked


def unstack_params_to_scope(plan, scope, stacked):
    for j, tname in enumerate(plan.template_params):
        arr = stacked[tname]
        for s in range(plan.n_stage):
            scope.set_var(plan.stage_params[s][j], arr[s])


def microbatch(x, n_micro):
    x = jnp.asarray(x)
    if x.shape[0] % n_micro:
        raise ValueError("batch %d not divisible by n_micro %d"
                         % (x.shape[0], n_micro))
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


class _KernelCtx(object):
    def rng(self):
        return jax.random.PRNGKey(0)


def make_update_fn(inner):
    """Functional (jittable) twin of a graph optimizer for the pipeline
    path, reusing the SAME ops/optimizer_ops kernels minimize() would
    append. v1 supports SGD / Momentum / Adam (+AdamW); the kernels are
    elementwise so they apply unchanged to (n_stage, ...) stacked params.

    Returns (init_fn(params)->state, update_fn(params, grads, state)
    -> (new_params, new_state)); params/grads/state are dicts of stacked
    arrays keyed by template param name."""
    from ..ops.registry import get_op
    name = type(inner).__name__
    lr = inner._learning_rate
    if callable(lr):
        raise ValueError("pipeline path needs a float learning rate (v1)")
    lrv = jnp.asarray([float(lr)], jnp.float32)
    ctx = _KernelCtx()

    if name == "SGDOptimizer":
        kern = get_op("sgd").fn

        def init_fn(params):
            return {}

        def update_fn(params, grads, state):
            new = {k: kern(ctx, {"Param": [p], "Grad": [grads[k]],
                                 "LearningRate": [lrv]}, {})["ParamOut"]
                   for k, p in params.items()}
            return new, state
    elif name == "MomentumOptimizer":
        kern = get_op("momentum").fn
        attrs = {"mu": inner._momentum,
                 "use_nesterov": inner._use_nesterov}

        def init_fn(params):
            return {k: jnp.zeros_like(p) for k, p in params.items()}

        def update_fn(params, grads, state):
            new_p, new_s = {}, {}
            for k, p in params.items():
                outs = kern(ctx, {"Param": [p], "Grad": [grads[k]],
                                  "Velocity": [state[k]],
                                  "LearningRate": [lrv]}, attrs)
                new_p[k] = outs["ParamOut"]
                new_s[k] = outs["VelocityOut"]
            return new_p, new_s
    elif name in ("AdamOptimizer", "AdamWOptimizer"):
        kern = get_op(inner._update_op).fn
        attrs = {"beta1": inner._beta1, "beta2": inner._beta2,
                 "epsilon": inner._epsilon, "lazy_mode": False}
        if name == "AdamWOptimizer":
            attrs.update(inner._extra_attrs())

        def init_fn(params):
            return {k: {"m1": jnp.zeros(p.shape, jnp.float32),
                        "m2": jnp.zeros(p.shape, jnp.float32),
                        "b1p": jnp.asarray([inner._beta1], jnp.float32),
                        "b2p": jnp.asarray([inner._beta2], jnp.float32)}
                    for k, p in params.items()}

        def update_fn(params, grads, state):
            new_p, new_s = {}, {}
            for k, p in params.items():
                s = state[k]
                outs = kern(ctx, {"Param": [p], "Grad": [grads[k]],
                                  "Moment1": [s["m1"]], "Moment2": [s["m2"]],
                                  "Beta1Pow": [s["b1p"]],
                                  "Beta2Pow": [s["b2p"]],
                                  "LearningRate": [lrv]}, attrs)
                new_p[k] = outs["ParamOut"]
                new_s[k] = {"m1": outs["Moment1Out"],
                            "m2": outs["Moment2Out"],
                            "b1p": outs["Beta1PowOut"],
                            "b2p": outs["Beta2PowOut"]}
            return new_p, new_s
    else:
        raise ValueError(
            "pipeline path supports SGD/Momentum/Adam/AdamW (v1); got %s"
            % name)
    return init_fn, update_fn


# ---------------------------------------------------------------------------
# CompiledProgram pp path: cut a MINIMIZED program (fwd + backward + update
# sections) for the single-shard_map pipelined step. Unlike the fleet path
# above (which replaces the optimizer with a functional twin), this cut
# keeps the program's OWN update section — optimizer ops, LR schedules,
# gradient-merge accumulation, grad clip — and re-runs it SPMD per stage.
# ---------------------------------------------------------------------------

class CompiledPPCut(object):
    """Everything the compiler needs to lower a minimized program through
    the GPipe/1F1B schedules inside one shard_map:

      plan         -- the forward-section PipelinePlan (stages + tail)
      update_ops   -- [(op, stage|None)] the post-backward non-grad ops in
                      program order; stage 0 + shared (None) ops are
                      traced, stage >= 1 ops are the SPMD copies the pp
                      shards realize implicitly
      stage_state  -- {template_name: [per-stage var names]} persistable
                      state stacked on the pp axis (params + optimizer
                      accumulators + grad-merge buffers)
      shared_state -- sorted per-replica persistable names (LR vars,
                      merge step counters): replicated on every shard
      loss_name    -- the var the backward section seeds
    """

    __slots__ = ("plan", "update_ops", "stage_state", "shared_state",
                 "loss_name")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    def signature(self):
        """Cut identity for the executor compile-cache token."""
        return (self.plan.n_stage, self.plan.schedule, self.plan.n_micro,
                tuple(self.plan.template_params),
                tuple(sorted(self.stage_state)),
                tuple(self.shared_state), self.loss_name)


def _map_stage_name(mapping, a, b, s):
    """Record stage-0 name ``a`` <-> stage-``s`` name ``b``; a name that
    maps two ways means the update sections are not positionally
    parallel — a cut we cannot run SPMD."""
    prev = mapping.get(a)
    if prev is None:
        mapping[a] = b
    elif prev != b:
        raise ValueError(
            "update section of pipeline stage %d is not positionally "
            "parallel to stage 0: stage-0 var %r maps to both %r and %r"
            % (s, a, prev, b))


def extract_compiled_pp_plan(program, n_stage=None, schedule="1f1b",
                             n_micro=1):
    """Cut a MINIMIZED program for the CompiledProgram pipeline path.

    The program is split at op_role boundaries: the forward section is
    stage-partitioned exactly like the fleet path (``pp_stage`` stamps,
    or an even op-count auto-cut when unstamped), the backward section
    is DROPPED (the schedule's in-shard_map autodiff replaces it), and
    the update section (everything after backward that is not a grad
    op: optimizer ops, LR schedule, gradient-merge accumulation) is
    validated to be per-stage homogeneous so each pp shard can run the
    stage-0 template on its own slice of the stacked state."""
    from ..framework.trace import GRAD_SUFFIX
    blk = program.global_block()
    ops = blk.ops
    first_bwd = next((i for i, op in enumerate(ops)
                      if op.attrs.get("op_role") == "backward"), None)
    if first_bwd is None:
        raise ValueError(
            "the CompiledProgram pipeline path lowers the whole "
            "fwd+bwd+optimizer step — minimize() the loss first (the "
            "program has no backward section)")
    seed_op = ops[first_bwd]
    if seed_op.type != "fill_any_like" or "X" not in seed_op.inputs:
        raise ValueError(
            "cannot identify the loss: the backward section does not "
            "start with the append_backward seed (multi-target "
            "gradients() programs are not supported on the pp path)")
    loss_name = seed_op.inputs["X"][0]
    fwd_ops = ops[:first_bwd]

    stamped = any("pp_stage" in op.attrs for op in fwd_ops)
    if stamped:
        plan = extract_pipeline_plan(program, loss_name, schedule=schedule,
                                     n_micro=n_micro, ops=fwd_ops)
        if n_stage is not None and plan.n_stage != int(n_stage):
            raise ValueError(
                "BuildStrategy.pp_stages=%d but the program is stamped "
                "with %d pipeline stages" % (int(n_stage), plan.n_stage))
    else:
        if not n_stage or int(n_stage) < 2:
            raise ValueError(
                "auto-cut needs BuildStrategy.pp_stages >= 2 when the "
                "program carries no pp_stage stamps")
        plan = _auto_stamp(program, fwd_ops, int(n_stage), loss_name,
                           schedule, n_micro)

    # ---- update section ---------------------------------------------------
    update_all = [op for op in ops[first_bwd:]
                  if op.attrs.get("op_role") != "backward"]
    stage_of = {}
    for s in range(plan.n_stage):
        for pname in plan.stage_params[s]:
            stage_of[pname] = s
            stage_of[pname + GRAD_SUFFIX] = s
    tagged = []
    for op in update_all:
        stages = {stage_of[nm] for nm in op.input_names()
                  if nm in stage_of}
        if len(stages) > 1:
            raise ValueError(
                "update op {%s} reads state of multiple pipeline stages "
                "(%r) — cross-stage update ops (e.g. a global grad-norm "
                "clip) are not supported on the pp path (v1)"
                % (op.type, sorted(stages)))
        s = stages.pop() if stages else None
        tagged.append((op, s))
        if s is not None:
            for nm in op.output_names():
                stage_of[nm] = s

    groups = {s: [op for op, st in tagged if st == s]
              for s in range(plan.n_stage)}
    sig0 = _stage_signature(groups[0])
    for s in range(1, plan.n_stage):
        if _stage_signature(groups[s]) != sig0:
            raise ValueError(
                "the update section for pipeline stage %d is not "
                "structurally identical to stage 0's — the SPMD pp path "
                "runs ONE update template on every stage's slice" % s)

    # positional stage-0 -> stage-s name maps (how the per-stage
    # optimizer state columns line up under the template)
    name_maps = [dict() for _ in range(plan.n_stage)]
    for s in range(1, plan.n_stage):
        for op0, op_s in zip(groups[0], groups[s]):
            for slot in op0.inputs:
                for a, b in zip(op0.inputs[slot],
                                op_s.inputs.get(slot, [])):
                    _map_stage_name(name_maps[s], a, b, s)
            for slot in op0.outputs:
                for a, b in zip(op0.outputs[slot],
                                op_s.outputs.get(slot, [])):
                    _map_stage_name(name_maps[s], a, b, s)

    def _persistable(nm):
        var = blk._find_var_recursive(nm)
        return var is not None and getattr(var, "persistable", False)

    stage_state = {}
    for j, tname in enumerate(plan.template_params):
        stage_state[tname] = [plan.stage_params[s][j]
                              for s in range(plan.n_stage)]
    for op0 in groups[0]:
        for nm in op0.output_names():
            if nm in stage_state or not _persistable(nm):
                continue
            cols = [nm] + [name_maps[s].get(nm, nm)
                           for s in range(1, plan.n_stage)]
            if len(set(cols)) != plan.n_stage:
                raise ValueError(
                    "per-stage update state %r does not map to a "
                    "distinct var per stage (got %r) — the stages "
                    "share state the SPMD cut cannot stack" % (nm, cols))
            stage_state[nm] = cols
    all_stage_names = {n for cols in stage_state.values() for n in cols}
    shared = set()
    for op, s in tagged:
        for nm in op.input_names() + op.output_names():
            if nm not in all_stage_names and _persistable(nm):
                shared.add(nm)
    return CompiledPPCut(plan=plan, update_ops=tagged,
                         stage_state=stage_state,
                         shared_state=sorted(shared),
                         loss_name=loss_name)


def _auto_stamp(program, fwd_ops, n_stage, loss_name, schedule, n_micro):
    """Even op-count auto-cut: stamp the LONGEST prefix of the forward
    section that splits into n_stage structurally identical, chaining
    segments; the remainder is the loss tail. Stamps stick (the program
    is mutated once; its version bumps so compiled steps re-key)."""
    n = len(fwd_ops)
    if n < n_stage:
        raise ValueError(
            "auto-cut cannot split %d forward ops into %d pipeline "
            "stages — lower pp_stages or stamp the model explicitly "
            "with pp_stage_guard(stage)" % (n, n_stage))
    last_err = None
    for seg in range(n // n_stage, 0, -1):
        cut = seg * n_stage
        for i, op in enumerate(fwd_ops):
            if i < cut:
                op.attrs["pp_stage"] = i // seg
            else:
                op.attrs.pop("pp_stage", None)
        try:
            plan = extract_pipeline_plan(program, loss_name,
                                         schedule=schedule,
                                         n_micro=n_micro, ops=fwd_ops)
            program._version += 1
            return plan
        except ValueError as e:
            last_err = e
    for op in fwd_ops:
        op.attrs.pop("pp_stage", None)
    raise ValueError(
        "auto-cut could not split the %d forward ops into %d "
        "homogeneous pipeline stages — stamp the model explicitly with "
        "pp_stage_guard(stage). Last attempt failed with: %s"
        % (n, n_stage, last_err))


# ---------------------------------------------------------------------------
# Elastic pp re-cut (ISSUE 18): stage -> slot re-mapping over a shrunk mesh.
# When a pp pod loses a host but the survivors can still hold every logical
# stage, the K stages are RE-STACKED over n_slots < K mesh slots — each slot
# runs a contiguous run of logical stages as one "super-stage" on the same
# GPipe/1F1B ring (ring size n_slots). The scope keeps the flat per-stage
# var layout, so checkpoints and elastic state-shipping stay wire-compatible;
# only the in-jit stacking geometry changes: (K, ...) -> (n_slots, k_per, ...).
# ---------------------------------------------------------------------------

class PPRecutError(ValueError):
    """A re-cut that cannot be built. ``reason`` is the typed label the
    elastic fallback stamps on its ``elastic_pp_rewind`` event so an
    operator can tell a policy refusal from a genuine infeasibility."""
    reason = "infeasible_slots"


class PPRecutInfeasibleError(PPRecutError):
    reason = "infeasible_slots"


class PPRecutHeterogeneousError(PPRecutError):
    reason = "heterogeneous_stages"


def recut_min_slots(k_stages):
    """The feasibility floor: K logical stages re-cut onto no fewer than
    ceil(K/2) slots (at most two stages per slot keeps the super-stage
    compute/stash growth bounded — the K-1..ceil(K/2) contract)."""
    return (int(k_stages) + 1) // 2


class RecutPlan(object):
    """A stage->slot re-mapping: K logical stages over n_slots mesh slots.

      counts[j]        -- logical stages resident in slot j (contiguous,
                          larger counts first, every slot non-empty; the
                          LAST logical stage always lands in the LAST
                          slot, so the schedules' is-last masking and the
                          loss seed work unchanged with ring size n_slots)
      starts[j]        -- first logical stage of slot j
      slot_of[s]       -- the slot logical stage s lives in
      k_per            -- max(counts): the stacked row count per slot
      stage_idx[j][i]  -- the logical stage stored at stacked row (j, i);
                          pad rows (i >= counts[j]) repeat the slot's last
                          real stage so the padded compute is numerically
                          benign — its output is discarded by the valid
                          mask and it is never written back to the scope
      valid[j][i]      -- True for real rows, False for pads
    """

    __slots__ = ("k_stages", "n_slots", "counts", "starts", "slot_of",
                 "k_per", "stage_idx", "valid")

    def __init__(self, k_stages, n_slots, counts, starts, slot_of, k_per,
                 stage_idx, valid):
        self.k_stages = k_stages
        self.n_slots = n_slots
        self.counts = counts
        self.starts = starts
        self.slot_of = slot_of
        self.k_per = k_per
        self.stage_idx = stage_idx
        self.valid = valid

    def signature(self):
        """Re-cut identity for the executor compile-cache token."""
        return (self.k_stages, self.n_slots, self.counts)


def recut_plan(k_stages, n_slots, stage_signatures=None):
    """Build the stage->slot re-mapping for K stages over n_slots slots.

    Balanced CONTIGUOUS partition, larger counts first: (3, 2) -> [2, 1],
    (4, 3) -> [2, 1, 1]. Raises the typed :class:`PPRecutError` family on
    an impossible request: n_slots < 1 or n_slots > k_stages
    (PPRecutInfeasibleError), or — when per-stage structural signatures
    are supplied — stages that are not structurally identical
    (PPRecutHeterogeneousError: a super-stage can only iterate one
    template)."""
    k, n = int(k_stages), int(n_slots)
    if k < 1:
        raise PPRecutInfeasibleError(
            "re-cut needs at least one logical stage; got k_stages=%d" % k)
    if n < 1:
        raise PPRecutInfeasibleError(
            "re-cut infeasible: %d pipeline stages cannot be re-stacked "
            "over %d mesh slots (need 1..%d)" % (k, n, k))
    if n > k:
        raise PPRecutInfeasibleError(
            "re-cut infeasible: %d slots exceed the %d logical stages — "
            "a slot cannot be empty (grow back to the 1-stage-per-slot "
            "plan instead)" % (n, k))
    if stage_signatures is not None:
        sigs = list(stage_signatures)
        if any(s != sigs[0] for s in sigs[1:]):
            raise PPRecutHeterogeneousError(
                "re-cut infeasible: pipeline stages are not structurally "
                "identical — the slot super-stage iterates ONE stage "
                "template over its resident stages")
    counts = tuple(k // n + (1 if j < k % n else 0) for j in range(n))
    starts, acc = [], 0
    for c in counts:
        starts.append(acc)
        acc += c
    starts = tuple(starts)
    slot_of = tuple(j for j, c in enumerate(counts) for _ in range(c))
    k_per = max(counts)
    stage_idx = tuple(
        tuple(starts[j] + min(i, counts[j] - 1) for i in range(k_per))
        for j in range(n))
    valid = tuple(tuple(i < counts[j] for i in range(k_per))
                  for j in range(n))
    return RecutPlan(k_stages=k, n_slots=n, counts=counts, starts=starts,
                     slot_of=slot_of, k_per=k_per, stage_idx=stage_idx,
                     valid=valid)


def make_slot_stage_fn(stage_fn, recut, axis_name="pp"):
    """Wrap a per-stage callable into the per-SLOT super-stage the
    re-cut ring runs: ``slot_fn({template_name: (k_per, ...)}, h) ->
    h_out`` iterates the slot's resident logical stages in chain order.
    Pad rows repeat the slot's last real stage (see RecutPlan), so their
    forward is well-conditioned; the valid mask discards their output
    and — through jnp.where's vjp — zeroes their gradient rows."""
    valid = np.asarray(recut.valid, bool)          # (n_slots, k_per)

    def slot_fn(params_me, h):
        slot = jax.lax.axis_index(axis_name)
        row_valid = jax.lax.dynamic_index_in_dim(
            jnp.asarray(valid), slot, 0, keepdims=False)
        for i in range(recut.k_per):
            p_i = {t: v[i] for t, v in params_me.items()}
            h = jnp.where(row_valid[i], stage_fn(p_i, h), h)
        return h

    return slot_fn


def make_update_trace_fn(program, cut):
    """The in-shard_map update-section runner: ``update(env)`` traces the
    stage-0 template + shared update ops IN PROGRAM ORDER over an env
    holding this shard's stage slice (template names), the schedule's
    dp-synced gradients and the replicated shared state. Mutates env."""
    from ..framework.trace import TraceContext, trace_op, GRAD_SUFFIX

    ops_to_run = [op for op, s in cut.update_ops if s in (None, 0)]

    def update(env):
        ctx = TraceContext(program,
                           jax.random.PRNGKey(program.random_seed))
        # the schedule already dp-synced the injected grads — the
        # quantized-collectives trace hook must not re-sync anything
        # the update section happens to (re)bind
        ctx.synced_grads.update(
            t + GRAD_SUFFIX for t in cut.plan.template_params)
        for i, op in enumerate(ops_to_run):
            trace_op(op, env, ctx, rng_tag=8000003 + i)

    return update
