"""Placeholder for the reference's generated pserver protobuf module
(ref fluid/distributed/ps_pb2.py, generated from ps.proto). There is no
pserver wire protocol on TPU; anything touching it raises with the
working alternative named."""

__all__ = []

_GUIDANCE = (
    "ps_pb2 is the reference pserver's wire protocol; paddle_tpu has no "
    "pserver tier — sparse state is row-sharded mesh arrays "
    "(distributed/sharded_embedding.py)")


def __getattr__(name):
    if name.startswith("__"):        # import-machinery dunder probes
        raise AttributeError(name)
    raise NotImplementedError(_GUIDANCE)
