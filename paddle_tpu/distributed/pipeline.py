"""Pipeline parallelism over the "pp" mesh axis.

Reference parity: fluid PipelineOptimizer + section_worker (device_worker.cc)
— the reference runs program "sections" on different GPUs connected by
queues. TPU-native: every chip on the pp axis holds ONE stage's weights;
a shard_map SPMD program runs `n_micro + n_stage - 1` ticks of lax.scan,
rotating microbatch activations around the ring with lax.ppermute (GPipe
schedule: the skew fills/drains the bubble). All chips execute the same
code — stage identity comes from lax.axis_index — which is exactly how XLA
wants MPMD expressed as SPMD.

This is a library-level facility (like ring_attention): stage functions are
JAX callables (e.g. built from dygraph layers or op kernels); the static
Program path reaches it through fleet strategy pp_stage_fns.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except Exception:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def _pvary(x, axis_name):
    try:
        return lax.pcast(x, (axis_name,), to="varying")
    except (AttributeError, TypeError):
        try:
            return lax.pvary(x, (axis_name,))
        except AttributeError:
            return x


def pipeline_forward(stage_fn, params_stacked, x_micro, mesh,
                     axis_name="pp"):
    """Run a GPipe forward over the pp ring.

    stage_fn(stage_params, h) -> h        (same signature every stage)
    params_stacked: pytree with leading dim n_stage (stage-sharded on pp)
    x_micro: (n_micro, micro_batch, ...) microbatched input
    Returns (n_micro, micro_batch, ...) outputs of the LAST stage.
    """
    n_stage = mesh.shape[axis_name]
    n_micro = x_micro.shape[0]
    ticks = n_micro + n_stage - 1
    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    def local_fn(params_local, x_local):
        # params_local: this stage's params (leading dim 1) ; x_local: all
        # microbatches (replicated input to stage 0)
        stage = lax.axis_index(axis_name)
        params_me = jax.tree.map(lambda p: p[0], params_local)
        h_shape = x_local.shape[1:]
        carry_in = _pvary(jnp.zeros(h_shape, x_local.dtype), axis_name)
        outputs = _pvary(jnp.zeros((n_micro,) + h_shape, x_local.dtype),
                         axis_name)

        def tick(state, t):
            carry, outputs = state
            # stage 0 ingests microbatch t (if any); others use carry
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            inject = lax.dynamic_index_in_dim(x_local, mb_idx, 0,
                                              keepdims=False)
            h_in = jnp.where(stage == 0, inject, carry)
            h_out = stage_fn(params_me, h_in)
            # last stage records its result for microbatch t - (n_stage-1)
            out_idx = jnp.clip(t - (n_stage - 1), 0, n_micro - 1)
            is_valid = (t >= n_stage - 1) & (stage == n_stage - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
            upd = jnp.where(is_valid, h_out, cur)
            outputs = lax.dynamic_update_index_in_dim(outputs, upd,
                                                      out_idx, 0)
            # rotate activations forward around the ring
            carry = lax.ppermute(h_out, axis_name, perm)
            return (carry, outputs), None

        (carry, outputs), _ = lax.scan(tick, (carry_in, outputs),
                                       jnp.arange(ticks))
        # only the last stage holds real outputs; broadcast to all so the
        # result is replicated (psum of one-hot contribution)
        contrib = jnp.where(stage == n_stage - 1, outputs,
                            jnp.zeros_like(outputs))
        return lax.psum(contrib, axis_name)

    fn = shard_map(
        local_fn, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), params_stacked),
                  P()),
        out_specs=P())
    return fn(params_stacked, x_micro)


def pipeline_loss_and_grads(stage_fn, loss_fn, params_stacked, x_micro,
                            y_micro, mesh, axis_name="pp"):
    """Differentiable pipeline step: mean loss over microbatches and grads
    for every stage's params (stage-sharded like the params)."""

    def total_loss(params_stacked):
        out = pipeline_forward(stage_fn, params_stacked, x_micro, mesh,
                               axis_name)
        return loss_fn(out, y_micro)

    return jax.value_and_grad(total_loss)(params_stacked)


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim
    (requires homogeneous stages, the GPipe-on-SPMD contract)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
