"""Pipeline parallelism over the "pp" mesh axis.

Reference parity: fluid PipelineOptimizer + section_worker (device_worker.cc)
— the reference runs program "sections" on different GPUs connected by
queues. TPU-native: every chip on the pp axis holds ONE stage's weights;
a shard_map SPMD program runs `n_micro + n_stage - 1` ticks of lax.scan,
rotating microbatch activations around the ring with lax.ppermute (GPipe
schedule: the skew fills/drains the bubble). All chips execute the same
code — stage identity comes from lax.axis_index — which is exactly how XLA
wants MPMD expressed as SPMD.

This is a library-level facility (like ring_attention): stage functions are
JAX callables (e.g. built from dygraph layers or op kernels); the static
Program path reaches it through fleet strategy pp_stage_fns.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except Exception:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def _pvary(x, axis_names):
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    # per-axis so an already-varying axis (e.g. zeros_like of pp-sharded
    # params) is simply skipped
    for a in axis_names:
        if not a:
            continue
        try:
            x = lax.pcast(x, (a,), to="varying")
        except ValueError:      # already varying on this axis
            pass
        except (AttributeError, TypeError):
            try:
                x = lax.pvary(x, (a,))
            except (AttributeError, ValueError):
                pass
    return x


def _data_spec(dp_axis):
    """Spec for (n_micro, micro_batch, ...) data: micro dim replicated,
    batch dim sharded over dp when a dp axis is in play."""
    return P(None, dp_axis) if dp_axis else P()


def pipeline_forward_local(stage_fn, n_stage, n_micro, axis_name="pp",
                           dp_axis=None, replicate_out=True):
    """The GPipe forward BODY — runs INSIDE a shard_map over the pp(xdp)
    mesh. Returns ``fwd(params_me, x_local) -> outputs``: params_me is
    THIS stage's params (no leading stage dim), x_local all microbatches
    (dp-sharded batch dim), outputs the last stage's results replicated
    over pp (psum of the one-hot contribution). Exposed so callers that
    already live inside one shard_map scope (the CompiledProgram pp
    path, which also traces the optimizer section in the same scope)
    can compose it; :func:`pipeline_forward` wraps it for library use.

    replicate_out=False skips the final pp psum and returns each
    shard's LOCAL outputs buffer (real results only on the last stage)
    — what a caller that differentiates INSIDE the shard_map needs:
    under check_rep=False the psum's transpose miscounts the replicated
    cotangent, so the loss must be masked to the last stage instead
    (see pipeline_gpipe_local)."""
    ticks = n_micro + n_stage - 1
    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
    vary_axes = (axis_name, dp_axis)

    def fwd(params_me, x_local):
        stage = lax.axis_index(axis_name)
        h_shape = x_local.shape[1:]
        carry_in = _pvary(jnp.zeros(h_shape, x_local.dtype), vary_axes)
        outputs = _pvary(jnp.zeros((n_micro,) + h_shape, x_local.dtype),
                         vary_axes)

        def tick(state, t):
            carry, outputs = state
            # stage 0 ingests microbatch t (if any); others use carry
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            inject = lax.dynamic_index_in_dim(x_local, mb_idx, 0,
                                              keepdims=False)
            h_in = jnp.where(stage == 0, inject, carry)
            h_out = stage_fn(params_me, h_in)
            # last stage records its result for microbatch t - (n_stage-1)
            out_idx = jnp.clip(t - (n_stage - 1), 0, n_micro - 1)
            is_valid = (t >= n_stage - 1) & (stage == n_stage - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
            upd = jnp.where(is_valid, h_out, cur)
            outputs = lax.dynamic_update_index_in_dim(outputs, upd,
                                                      out_idx, 0)
            # rotate activations forward around the ring
            carry = lax.ppermute(h_out, axis_name, perm)
            return (carry, outputs), None

        (carry, outputs), _ = lax.scan(tick, (carry_in, outputs),
                                       jnp.arange(ticks))
        if not replicate_out:
            return outputs
        # only the last stage holds real outputs; broadcast to all so the
        # result is replicated (psum of one-hot contribution)
        contrib = jnp.where(stage == n_stage - 1, outputs,
                            jnp.zeros_like(outputs))
        return lax.psum(contrib, axis_name)

    return fwd


def pipeline_forward(stage_fn, params_stacked, x_micro, mesh,
                     axis_name="pp", dp_axis=None):
    """Run a GPipe forward over the pp ring.

    stage_fn(stage_params, h) -> h        (same signature every stage)
    params_stacked: pytree with leading dim n_stage (stage-sharded on pp)
    x_micro: (n_micro, micro_batch, ...) microbatched input
    dp_axis: optional second mesh axis the micro-batch dim is sharded over
    (dp x pp: params replicated over dp, XLA psums their grads there).
    Returns (n_micro, micro_batch, ...) outputs of the LAST stage.
    """
    n_stage = mesh.shape[axis_name]
    n_micro = x_micro.shape[0]
    fwd = pipeline_forward_local(stage_fn, n_stage, n_micro, axis_name,
                                 dp_axis)

    def local_fn(params_local, x_local):
        params_me = jax.tree.map(lambda p: p[0], params_local)
        return fwd(params_me, x_local)

    fn = shard_map(
        local_fn, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), params_stacked),
                  _data_spec(dp_axis)),
        out_specs=_data_spec(dp_axis))
    return fn(params_stacked, x_micro)


def pipeline_gpipe_local(stage_fn, loss_fn, n_stage, n_micro,
                         axis_name="pp", dp_axis=None):
    """GPipe loss+grads BODY for single-shard_map callers (the
    CompiledProgram pp path): ``step(params_me, x_local, y_local) ->
    (loss, grads_me)`` with autodiff run INSIDE the shard_map scope
    (vjp of the local forward; ppermute/psum transpose to the reverse
    ring). loss_fn(h_m, y_m) -> scalar per-microbatch loss; loss/grads
    are the mean over microbatches, pp-replicated. Like
    :func:`pipeline_1f1b_local` the dp reduction is LEFT TO THE CALLER
    (grads come back dp-varying) so a quantized or otherwise custom dp
    gradient sync can slot in."""
    # NO final psum in the differentiated forward: under check_rep=False
    # the psum transpose miscounts a replicated cotangent. The loss is
    # masked to the last stage instead — its cotangent rides the reverse
    # ppermute ring back through the stages, and the scalar loss is
    # pp-psum'd OUTSIDE the grad computation.
    fwd = pipeline_forward_local(stage_fn, n_stage, n_micro, axis_name,
                                 dp_axis, replicate_out=False)

    def step(params_me, x_local, y_local):
        stage = lax.axis_index(axis_name)
        is_last = stage == n_stage - 1
        # dp-varying params keep each shard's cotangent local; the
        # caller runs ONE dp reduction for the whole step (same trick
        # as pipeline_1f1b_step's params_vjp)
        params_vjp = params_me if dp_axis is None else jax.tree.map(
            lambda p: _pvary(p, (dp_axis,)), params_me)

        def total(ps):
            out = fwd(ps, x_local)
            losses = jax.vmap(loss_fn)(out, y_local)
            local = jnp.mean(losses.astype(jnp.float32))
            # non-last stages ran loss_fn on their (zeros) local buffer:
            # mask it out — where's vjp seeds the untaken side with zero
            return jnp.where(is_last, local, 0.0)

        loss, grads = jax.value_and_grad(total)(params_vjp)
        loss = lax.psum(loss, axis_name)
        return loss, grads

    return step


def pipeline_loss_and_grads(stage_fn, loss_fn, params_stacked, x_micro,
                            y_micro, mesh, axis_name="pp", dp_axis=None):
    """Differentiable pipeline step: mean loss over microbatches and grads
    for every stage's params (stage-sharded like the params). With dp_axis
    the micro-batch dim is dp-sharded; AD's shard_map transpose inserts the
    dp psum on parameter grads automatically."""

    def total_loss(params_stacked):
        out = pipeline_forward(stage_fn, params_stacked, x_micro, mesh,
                               axis_name, dp_axis=dp_axis)
        return loss_fn(out, y_micro)

    return jax.value_and_grad(total_loss)(params_stacked)


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim
    (requires homogeneous stages, the GPipe-on-SPMD contract)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_1f1b_step(stage_fn, loss_fn, params_stacked, x_micro, y_micro,
                       mesh, axis_name="pp", dp_axis=None):
    """1F1B pipeline schedule (reference PipelineOptimizer's successor
    schedule; fluid's section_worker runs plain GPipe).

    Each scan tick performs ONE forward micro-step and ONE backward
    micro-step per stage, so at most ~2*n_stage microbatch activations are
    stashed per stage — GPipe-via-autodiff (pipeline_loss_and_grads) stashes
    all n_micro. Backward uses per-tick jax.vjp on the stashed stage INPUT
    (rematerialization: one extra forward per micro-step, the standard TPU
    trade of FLOPs for HBM).

    Schedule (stage s of n, tick k):
      forward  of microbatch  mf = k - s
      backward of microbatch  mb = k - (n-1) - (n-1-s)
    The last stage backpropagates a microbatch in the same tick its forward
    completes; grads ride the reverse ring one stage per tick, exactly one
    tick behind the stage above — the classic 1F1B steady state.

    loss_fn(h_out, y_one_micro) -> scalar per-microbatch loss; the returned
    loss/grads correspond to  mean_m loss_fn(chain(x_m), y_m).

    Returns (loss, grads_stacked) with grads sharded like params_stacked.
    """
    n_stage = mesh.shape[axis_name]
    n_micro = x_micro.shape[0]
    step = pipeline_1f1b_local(stage_fn, loss_fn, n_stage, n_micro,
                               axis_name, dp_axis)

    def local_fn(params_local, x_local, y_local):
        params_me = jax.tree.map(lambda p: p[0], params_local)
        loss, grads = step(params_me, x_local, y_local)
        if dp_axis is not None:
            # one batched dp reduction for the whole step (see the
            # params_vjp note inside the local body)
            loss = lax.pmean(loss, dp_axis)
            grads = jax.tree.map(lambda g: lax.pmean(g, dp_axis), grads)
        grads = jax.tree.map(lambda g: g[None], grads)
        return loss, grads

    fn = shard_map(
        local_fn, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), params_stacked),
                  _data_spec(dp_axis),
                  jax.tree.map(lambda _: _data_spec(dp_axis), y_micro)),
        out_specs=(P(), jax.tree.map(lambda _: P(axis_name), params_stacked)))
    return fn(params_stacked, x_micro, y_micro)


def pipeline_1f1b_local(stage_fn, loss_fn, n_stage, n_micro,
                        axis_name="pp", dp_axis=None):
    """The 1F1B schedule BODY — runs INSIDE a shard_map over the pp(xdp)
    mesh: ``step(params_me, x_local, y_local) -> (loss, grads_me)``.
    params_me/grads_me carry NO leading stage dim (this shard's stage);
    loss is the microbatch mean, pp-replicated via psum. The dp
    reduction is deliberately LEFT TO THE CALLER — grads (and loss)
    come back dp-varying so a custom sync (e.g. the quantized
    collectives' quantize->psum->dequantize) can replace the plain
    pmean. :func:`pipeline_1f1b_step` wraps this with the shard_map +
    pmean defaults."""
    ticks = n_micro + 2 * (n_stage - 1)
    slots = 2 * n_stage
    perm_fwd = [(i, (i + 1) % n_stage) for i in range(n_stage)]
    perm_bwd = [(i, (i - 1) % n_stage) for i in range(n_stage)]

    vary_axes = (axis_name, dp_axis)

    def step(params_me, x_local, y_local):
        stage = lax.axis_index(axis_name)
        h_shape = x_local.shape[1:]
        dtype = x_local.dtype
        zero_h = jnp.zeros(h_shape, dtype)

        def fwd_of(h_in):
            return stage_fn(params_me, h_in)

        # params_me is REPLICATED over dp, so a vjp against it would make
        # shard_map's AD insert a param-sized dp psum EVERY tick. Marking
        # the params dp-varying first keeps each tick's cotangent local;
        # one psum after the scan does the whole reduction.
        params_vjp = params_me if dp_axis is None else jax.tree.map(
            lambda p: _pvary(p, (dp_axis,)), params_me)

        init = dict(
            fwd_carry=_pvary(zero_h, vary_axes),
            bwd_carry=_pvary(zero_h, vary_axes),
            stash=_pvary(jnp.zeros((slots,) + h_shape, dtype), vary_axes),
            grad_acc=jax.tree.map(
                lambda p: _pvary(jnp.zeros_like(p), vary_axes), params_me),
            loss_acc=_pvary(jnp.zeros((), jnp.float32), vary_axes),
        )

        def tick(state, k):
            mf = k - stage
            fwd_valid = (mf >= 0) & (mf < n_micro)
            mf_c = jnp.clip(mf, 0, n_micro - 1)
            mb = k - (n_stage - 1) - (n_stage - 1 - stage)
            bwd_valid = (mb >= 0) & (mb < n_micro)
            mb_c = jnp.clip(mb, 0, n_micro - 1)

            # ---- forward micro-step ------------------------------------
            inject = lax.dynamic_index_in_dim(x_local, mf_c, 0,
                                              keepdims=False)
            h_in = jnp.where(stage == 0, inject, state["fwd_carry"])
            h_out = fwd_of(h_in)
            stash = jnp.where(
                fwd_valid,
                lax.dynamic_update_index_in_dim(
                    state["stash"], h_in, mf_c % slots, 0),
                state["stash"])

            # last stage: per-micro loss + gradient seed, both this tick
            # (y may be a pytree of several label/aux feeds — tree.map
            # also handles the single-array case)
            y_m = jax.tree.map(
                lambda y: lax.dynamic_index_in_dim(y, mf_c, 0,
                                                   keepdims=False), y_local)
            loss_m, loss_vjp = jax.vjp(lambda h: loss_fn(h, y_m), h_out)
            is_last = stage == n_stage - 1
            loss_acc = state["loss_acc"] + jnp.where(
                fwd_valid & is_last,
                loss_m.astype(jnp.float32).reshape(()), 0.0)
            (g_seed,) = loss_vjp(jnp.ones_like(loss_m))

            # ---- backward micro-step (rematerialized vjp) --------------
            h_in_b = lax.dynamic_index_in_dim(stash, mb_c % slots, 0,
                                              keepdims=False)
            _, stage_vjp = jax.vjp(stage_fn, params_vjp, h_in_b)
            g_out = jnp.where(is_last, g_seed, state["bwd_carry"])
            dparams, dh_in = stage_vjp(g_out.astype(dtype))
            grad_acc = jax.tree.map(
                lambda a, g: a + jnp.where(bwd_valid, g, 0.0),
                state["grad_acc"], dparams)

            return dict(
                fwd_carry=lax.ppermute(h_out, axis_name, perm_fwd),
                bwd_carry=lax.ppermute(
                    jnp.where(bwd_valid, dh_in, jnp.zeros_like(dh_in)),
                    axis_name, perm_bwd),
                stash=stash, grad_acc=grad_acc, loss_acc=loss_acc), None

        state, _ = lax.scan(tick, init, jnp.arange(ticks))
        loss = lax.psum(state["loss_acc"], axis_name) / n_micro
        grads = jax.tree.map(lambda g: g / n_micro, state["grad_acc"])
        return loss, grads

    return step
