"""Downpour-SGD surface (ref fluid/distributed/downpour.py).

The reference's DownpourSGD configured Baidu's async parameter-server
tables. On TPU pods the capability (huge sparse tables + distributed
updates) is row-sharded mesh state with synchronous XLA collectives —
see distributed/sharded_embedding.py and PORTING.md "Capability
substitutions". The class is kept so ported configs fail loudly AT THE
RIGHT LINE with the working alternative named.
"""

__all__ = ["DownpourSGD"]

_GUIDANCE = (
    "DownpourSGD configures the reference's async pserver tables, which "
    "do not exist on TPU; use embedding(..., is_distributed=True) for "
    "row-sharded tables and a lazy-mode Adam/SGD from paddle_tpu."
    "optimizer — sync dp over ICI replaces async push/pull")


class DownpourSGD(object):
    def __init__(self, learning_rate=0.001, window=1):
        raise NotImplementedError(_GUIDANCE)
