"""Sharded embedding tables — the TPU-native parameter-server replacement.

Reference parity: operators/distributed/* + distribute_transpiler's pserver
path, whose job is ONE thing — keep an embedding table too big for one
device and serve sparse lookup/update. On a TPU pod there are no parameter
server processes: the table is row-sharded over a mesh axis, lookups are a
local masked gather + psum over that axis (each id's row lives on exactly
one shard, so the psum sums one hit and zeros), and the backward is the
transposed scatter-add into the local shard — XLA keeps every update local
to the owner shard. Pair with Adam(lazy_mode=True) for row-sparse moments.
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except Exception:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def sharded_embedding_lookup(table, ids, mesh, axis="mp"):
    """Lookup rows of a row-sharded table.

    table: (V, D) sharded on rows over `axis` (V divisible by axis size)
    ids:   int array, any shape, replicated
    Returns ids.shape + (D,), replicated. Differentiable w.r.t. table; the
    cotangent is the dense scatter-add restricted to each owner shard.
    """
    n_shard = mesh.shape[axis]
    v, d = table.shape
    rows_per = v // n_shard

    def local_fn(tbl, ids_local):
        shard = lax.axis_index(axis)
        lo = shard * rows_per
        local = ids_local - lo
        hit = (local >= 0) & (local < rows_per)
        safe = jnp.clip(local, 0, rows_per - 1)
        vals = tbl[0][safe]                       # (..., D) local gather
        vals = jnp.where(hit[..., None], vals, 0)
        return lax.psum(vals, axis)

    fn = shard_map(local_fn, mesh=mesh,
                   in_specs=(P(axis), P()), out_specs=P())
    return fn(table.reshape(n_shard, rows_per, d), ids)


class ShardedEmbedding(object):
    """Big-table embedding living row-sharded on the mesh (pserver-table
    equivalent). Keeps the table as a device array with a NamedSharding so
    optimizer updates stay shard-local under jit."""

    def __init__(self, num_embeddings, dim, mesh, axis="mp", scale=0.01,
                 seed=0, dtype=jnp.float32):
        if num_embeddings % mesh.shape[axis]:
            raise ValueError("num_embeddings must divide the %r axis size"
                             % axis)
        self.mesh = mesh
        self.axis = axis
        self.num_embeddings = num_embeddings
        self.dim = dim
        key = jax.random.PRNGKey(seed)
        host = jax.random.normal(key, (num_embeddings, dim), dtype) * scale
        self.table = jax.device_put(
            host, NamedSharding(mesh, P(axis, None)))

    def __call__(self, ids):
        return sharded_embedding_lookup(self.table, ids, self.mesh,
                                        self.axis)

    def apply_row_sparse_grad(self, grad, lr):
        """SGD row update; grad is the dense cotangent (zero rows for
        untouched ids). Sharded identically to the table, so the update
        is local per shard."""
        self.table = self.table - lr * grad


def distributed_embedding_attr(name=None, axis="mp", **kw):
    """ParamAttr annotating a static-graph embedding table as row-sharded
    (the is_distributed=True path of layers.embedding): CompiledProgram
    places it with NamedSharding(mesh, (axis, None)) and XLA partitions
    lookups/updates across shards."""
    from ..param_attr import ParamAttr
    return ParamAttr(name=name, sharding=(axis, None), **kw)
