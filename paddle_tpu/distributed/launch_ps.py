"""Parameter-server job launcher (ref python/paddle/distributed/
launch_ps.py). TPU pods have no pserver/trainer split — every host runs
the same SPMD program — so this entry point delegates to the collective
launcher and says so."""
import sys

__all__ = ["main"]


def main(args=None):
    sys.stderr.write(
        "launch_ps starts pserver+trainer process groups, which do not "
        "exist on TPU; launching the collective SPMD job via "
        "paddle_tpu.distributed.launch instead\n")
    from . import launch
    return launch.launch(args)


if __name__ == "__main__":  # pragma: no cover
    main()
