"""Multi-process / multi-host launcher
(ref python/paddle/distributed/launch.py).

The reference spawns one trainer process per GPU with
PADDLE_TRAINER_ID/PADDLE_CURRENT_ENDPOINT env wiring.  On TPU pods the
runtime model differs: ONE process per host drives all local chips and
`jax.distributed.initialize` forms the job.  This module covers both
worlds:

* ``init_on_pod()`` — call at the top of a training script on every
  host: reads the reference's env contract (PADDLE_TRAINERS_NUM,
  PADDLE_TRAINER_ID, PADDLE_TRAINER_ENDPOINTS) or the TPU runtime's
  own discovery, then calls ``jax.distributed.initialize`` so the
  global mesh sees every host's chips.
* ``python -m paddle_tpu.distributed.launch --nproc_per_node=N
  script.py`` — local simulation: spawns N CPU processes with the env
  contract set (each with a coordinator address), mirroring the
  reference CLI for development boxes without a pod.
"""
import os
import signal
import subprocess
import sys
import time

__all__ = ["init_on_pod", "get_cluster_env", "start_procs", "launch"]


def get_cluster_env(env=None):
    """Parse the fluid launcher env contract -> (num_hosts, host_id,
    endpoints, coordinator)."""
    env = env if env is not None else os.environ
    num = int(env.get("PADDLE_TRAINERS_NUM", env.get("PADDLE_NUM_TRAINERS",
                                                     "1")))
    hid = int(env.get("PADDLE_TRAINER_ID", "0"))
    eps = [e for e in env.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
           if e]
    coordinator = eps[0] if eps else env.get("PADDLE_COORDINATOR",
                                             "127.0.0.1:8476")
    return num, hid, eps, coordinator


def init_on_pod(mesh_axes=None, env=None):
    """Initialize multi-host JAX from the fluid env contract and install
    the global mesh.  Idempotent; single-host jobs skip the distributed
    handshake entirely."""
    import jax
    num, hid, _eps, coordinator = get_cluster_env(env)
    if num > 1:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator, num_processes=num,
                process_id=hid)
        except (RuntimeError, ValueError) as e:  # already initialized
            if "already" not in str(e):
                raise
    else:
        e = env if env is not None else os.environ
        # no fluid env contract: fall back to the TPU runtime's own
        # discovery.  The pod check must NOT touch jax.default_backend()
        # (that would initialize the backend before
        # jax.distributed.initialize, which must run first), so key off
        # the TPU VM runtime's env instead.
        on_pod = e.get("PADDLE_TRAINERS_NUM") is None and (
            "TPU_WORKER_HOSTNAMES" in e or "MEGASCALE_COORDINATOR_ADDRESS"
            in e)
        if on_pod:
            hosts = [h for h in e.get("TPU_WORKER_HOSTNAMES",
                                      "").split(",") if h]
            multi_host = len(hosts) > 1 or \
                "MEGASCALE_COORDINATOR_ADDRESS" in e
            try:
                jax.distributed.initialize()
            except (RuntimeError, ValueError) as err:
                if "already" in str(err):
                    pass
                elif multi_host:
                    # a genuine pod MUST form the job — N silent
                    # single-process copies would train garbage
                    raise
                else:
                    # single-host TPU VMs also set the pod env vars; a
                    # failed discovery there degrades to a working
                    # 1-process job, loudly
                    import warnings
                    warnings.warn(
                        "jax.distributed.initialize() discovery failed "
                        "(%s); continuing as a single-process job — on "
                        "a real pod set the PADDLE_TRAINER_* env "
                        "contract instead" % (err,))
    if mesh_axes:
        from . import mesh as mesh_mod
        mesh_mod.init_mesh(mesh_axes)
    return jax.process_index(), jax.process_count()


def start_procs(nproc, training_script, script_args=(), log_dir=None,
                base_port=8476, env=None):
    """Spawn *nproc* local worker processes with the env contract set
    (ref launch.py:147).  Workers run on the CPU backend so a dev box
    can exercise the multi-process path; returns the Popen list."""
    base_env = dict(env if env is not None else os.environ)
    eps = ",".join("127.0.0.1:%d" % (base_port + i) for i in range(nproc))
    procs = []
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for i in range(nproc):
        cur = dict(base_env)
        cur.update({
            "PADDLE_TRAINERS_NUM": str(nproc),
            "PADDLE_TRAINER_ID": str(i),
            "PADDLE_TRAINER_ENDPOINTS": eps,
            "PADDLE_CURRENT_ENDPOINT": "127.0.0.1:%d" % (base_port + i),
            "JAX_PLATFORMS": "cpu",
        })
        cmd = [sys.executable, "-u", training_script] + list(script_args)
        if log_dir:
            with open(os.path.join(log_dir, "workerlog.%d" % i),
                      "w") as out:
                # Popen dups the fd; closing the parent copy immediately
                # avoids leaking one handle per spawned worker
                procs.append(subprocess.Popen(
                    cmd, env=cur, stdout=out, stderr=subprocess.STDOUT))
        else:
            procs.append(subprocess.Popen(cmd, env=cur))
    return procs


def terminate_procs(procs):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    time.sleep(1)
    for p in procs:
        if p.poll() is None:
            p.kill()


def launch(argv=None):
    """CLI entry (ref launch.py:283): ``--nproc_per_node N script.py
    [args...]``; waits for workers, propagates the first failure."""
    import argparse
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("--started_port", type=int, default=8476)
    parser.add_argument("training_script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    procs = start_procs(args.nproc_per_node, args.training_script,
                        args.script_args, log_dir=args.log_dir,
                        base_port=args.started_port)
    rc = 0
    try:
        for p in procs:
            p.wait()
            rc = rc or p.returncode
    finally:
        terminate_procs(procs)
    if rc:
        sys.exit(rc)


if __name__ == "__main__":
    launch()
