"""Ulysses-style sequence parallelism — all-to-all context sharding.

The second long-context strategy next to ring attention
(ring_attention.py): instead of rotating K/V blocks around a ring, two
`lax.all_to_all` exchanges re-shard the tensors from sequence-sharded
(B, H, T/n, D) to head-sharded (B, H/n, T, D), run EXACT full attention
per local head group through the Pallas flash kernel (O(T) memory), and
swap back. Trade-offs vs ring:

  * communication is 2 all-to-alls of activation size, independent of
    sequence length steps — better when T is huge and H/n >= 1;
  * each device sees the FULL sequence for its heads, so any attention
    variant (masks, dropout, alibi) works unchanged;
  * requires num_heads % n == 0 (ring has no such constraint).

No reference counterpart (the reference caps at single-device
attention); pattern from the DeepSpeed-Ulysses paper, re-expressed as
shard_map + lax.all_to_all over a mesh axis so XLA schedules the
exchanges on ICI.
"""
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map_mod
    shard_map = _shard_map_mod
except Exception:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def _local_attention(q, k, v, scale, causal, mask=None):
    """Exact attention on the local head group over the FULL sequence —
    through the Pallas flash kernel (O(T) memory, VMEM-tiled online
    softmax; falls back to fused XLA attention off-TPU / for small
    tiles), so long sequences never materialize (T, T) scores."""
    from ..ops.pallas.flash_attention import flash_attention
    return flash_attention(q, k, v, mask=mask, scale=scale, causal=causal)


def _make_local(axis_name, causal, scale, mask_gather_axis=None):
    def local(q, k, v, *mask_arg):
        # (B, H, T/n, D) local -> all_to_all -> (B, H/n, T, D) local:
        # split the head axis across the group, concatenate the seq axis
        qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
        kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
        vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
        mask = None
        if mask_arg:
            # additive masks have no head axis to exchange (dim 1 is
            # broadcast): gather the full sequence axis instead — each
            # device now sees the full sequence for its head group, so
            # any mask shape works unchanged
            mask = lax.all_gather(mask_arg[0], axis_name,
                                  axis=mask_gather_axis, tiled=True)
        out = _local_attention(qh, kh, vh, scale, causal, mask)
        # inverse exchange: heads back together, sequence re-sharded
        return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
    return local


def ulysses_attention(q, k, v, mask=None, mesh=None, axis_name="sp",
                      causal=False, scale=None):
    """q,k,v: (B, H, T, D) arrays (or sharded jax.Arrays); T sharded on
    `axis_name`. num_heads must divide by the axis size. `mask` is an
    optional ADDITIVE attention mask: key-padding (..., 1, T) masks are
    sharded on their key axis, per-query (..., Tq, Tk) masks on their
    query axis; either is all-gathered inside the shard (each device
    sees the full sequence for its head group, so any mask works).
    Returns attention output with the same sharding as the inputs."""
    from .mesh import get_mesh
    mesh = mesh or get_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        raise ValueError("ulysses_attention needs a mesh with axis %r"
                         % axis_name)
    n = mesh.shape[axis_name]
    if q.shape[1] % n:
        raise ValueError(
            "ulysses_attention: num_heads (%d) must divide the %r axis "
            "size (%d) — use ring_attention for head counts that don't"
            % (q.shape[1], axis_name, n))
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, None, axis_name, None)
    in_specs = (spec, spec, spec)
    args = (q, k, v)
    gather_axis = None
    if mask is not None:
        # shard the mask on its sequence axis: key axis for key-padding
        # masks (dim -2 == 1), query axis for per-query masks
        gather_axis = mask.ndim - 1 if mask.shape[-2] == 1 else mask.ndim - 2
        if mask.shape[gather_axis] % n:
            raise ValueError(
                "ulysses_attention: mask axis %d (size %d) must divide "
                "the %r axis size (%d)"
                % (gather_axis, mask.shape[gather_axis], axis_name, n))
        mspec = [None] * mask.ndim
        mspec[gather_axis] = axis_name
        in_specs = in_specs + (P(*mspec),)
        args = args + (mask,)
    local = _make_local(axis_name, causal, scale, gather_axis)
    try:
        # the flash pallas_call's output avals carry no vma annotation,
        # so varying-mode checking must be off inside this body
        fn = shard_map(local, mesh=mesh, in_specs=in_specs,
                       out_specs=spec, check_vma=False)
    except TypeError:  # pragma: no cover - older jax: check_rep
        fn = shard_map(local, mesh=mesh, in_specs=in_specs,
                       out_specs=spec, check_rep=False)
    return fn(*args)
