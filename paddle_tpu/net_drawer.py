"""fluid.net_drawer parity (ref python/paddle/fluid/net_drawer.py):
renders the MAIN program's graph via the debugger's DOT writer."""
from .debugger import draw_block_graphviz  # noqa: F401
from .debugger import draw_program as _draw_program

__all__ = ["draw_graph"]


def draw_graph(startup_program, main_program, **kwargs):
    """Reference signature (net_drawer.py:103): draws the main program;
    graph_path/filename kwargs name the output DOT file."""
    path = kwargs.get("graph_path") or kwargs.get("filename")
    return _draw_program(main_program, path=path)
