"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid (reference: /root/reference, PaddlePaddle v1.6).

Front-end: fluid-compatible static-graph Program/Block/Op IR, layers,
optimizers, executors — so fluid model code ports nearly verbatim.
Back-end: every op is a pure JAX function; the Executor traces a whole
Program (forward+backward+optimizer) into ONE jax.jit/pjit XLA computation
with donated parameter buffers; distribution is jax.sharding over a TPU mesh
(ICI collectives), not parameter servers.
"""
from . import ops            # registers all op kernels
from .framework import (Program, Variable, Parameter, default_main_program,
                        default_startup_program, program_guard, name_scope,
                        TPUPlace, CPUPlace, Scope, global_scope, scope_guard,
                        Executor, CompiledProgram, BuildStrategy,
                        ExecutionStrategy, unique_name)
from .framework.backward import append_backward, gradients
from .param_attr import ParamAttr, WeightNormParamAttr
from . import initializer
from . import layers
from . import nets
from . import optimizer
from . import regularizer
from . import clip
from . import metrics
from . import evaluator
from . import utils
from . import io
from .io import (save_params, save_persistables, load_params,
                 load_persistables, save_inference_model,
                 load_inference_model)
from . import reader
from .data_feeder import DataFeeder
from .reader.decorator import batch  # paddle.batch parity
from . import dygraph
from . import distributed
from . import inference
from . import contrib
from . import native
from . import profiler
from . import debugger
from . import dataset
from .dataset import DatasetFactory
from .parallel_executor import ParallelExecutor
from . import average
from . import incubate
from . import transpiler
from .transpiler import (DistributeTranspiler, DistributeTranspilerConfig,
                         memory_optimize, release_memory)
from . import lod_tensor as lod_tensor_mod
from .lod_tensor import (LoDTensor, create_lod_tensor,
                         create_random_int_lodtensor)
from .framework.compiler import make_mesh
from .data import data  # fluid.data: full-shape, None dims (ref fluid/data.py)
from .data_feed_desc import DataFeedDesc
from .input import one_hot, embedding
from .core import CUDAPlace, CUDAPinnedPlace
from .install_check import run_check

__version__ = "0.1.0"


def cuda_places(device_ids=None):
    """API-compat shim: on TPU builds, 'accelerator places' are TPU chips."""
    import jax
    n = len(jax.devices())
    ids = range(n) if device_ids is None else device_ids
    return [TPUPlace(i) for i in ids]


def tpu_places(device_ids=None):
    import jax
    n = len(jax.devices())
    ids = range(n) if device_ids is None else device_ids
    return [TPUPlace(i) for i in ids]


def cpu_places(device_count=None):
    return [CPUPlace()]


def in_dygraph_mode():
    """ref framework.in_dygraph_mode."""
    return dygraph.enabled()


def is_compiled_with_cuda():
    """ref framework.is_compiled_with_cuda — always False: the
    accelerator is TPU (see tpu_places)."""
    return False


def cuda_pinned_places(device_count=None):
    """ref framework.cuda_pinned_places — host staging on TPU is plain
    host memory; returns CPU places."""
    return [CPUPlace()] * (device_count or 1)


def require_version(min_version, max_version=None):
    """ref framework.require_version, against paddle_tpu's version."""
    def parse(v):
        return [int(x) for x in str(v).split(".") if x.isdigit()]
    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            "paddle_tpu version %s is below required %s" %
            (__version__, min_version))
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            "paddle_tpu version %s is above allowed %s" %
            (__version__, max_version))


def load_op_library(lib_path):
    """ref framework.load_op_library (custom C++/CUDA op .so).  Custom
    ops here are pure JAX kernels: register with
    paddle_tpu.ops.registry.register_op instead."""
    raise NotImplementedError(
        "load_op_library loads CUDA kernels; on paddle_tpu register a "
        "JAX kernel via paddle_tpu.ops.registry.register_op (see "
        "ops/registry.py docstring)")


# `import paddle_tpu; paddle_tpu.fluid.layers...` — the reference's
# paddle.fluid spelling, aliased onto this package (fluid/__init__.py)
from . import fluid  # noqa: E402

# deep reference module paths (slim/prune/pruner.py-style packages that
# are flat modules here) registered as virtual re-export modules
from . import _compat_submodules  # noqa: E402
_compat_submodules.install()
