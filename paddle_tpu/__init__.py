"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid (reference: /root/reference, PaddlePaddle v1.6).

Front-end: fluid-compatible static-graph Program/Block/Op IR, layers,
optimizers, executors — so fluid model code ports nearly verbatim.
Back-end: every op is a pure JAX function; the Executor traces a whole
Program (forward+backward+optimizer) into ONE jax.jit/pjit XLA computation
with donated parameter buffers; distribution is jax.sharding over a TPU mesh
(ICI collectives), not parameter servers.
"""
from . import ops            # registers all op kernels
from .framework import (Program, Variable, Parameter, default_main_program,
                        default_startup_program, program_guard, name_scope,
                        TPUPlace, CPUPlace, Scope, global_scope, scope_guard,
                        Executor, CompiledProgram, BuildStrategy,
                        ExecutionStrategy, unique_name)
from .framework.backward import append_backward, gradients
from .param_attr import ParamAttr, WeightNormParamAttr
from . import initializer
from . import layers
from . import nets
from . import optimizer
from . import regularizer
from . import clip
from . import metrics
from . import evaluator
from . import utils
from . import io
from .io import (save_params, save_persistables, load_params,
                 load_persistables, save_inference_model,
                 load_inference_model)
from . import reader
from .data_feeder import DataFeeder
from .reader.decorator import batch  # paddle.batch parity
from . import dygraph
from . import distributed
from . import inference
from . import contrib
from . import native
from . import profiler
from . import debugger
from . import dataset
from .dataset import DatasetFactory
from .parallel_executor import ParallelExecutor
from . import average
from . import incubate
from . import transpiler
from .transpiler import (DistributeTranspiler, DistributeTranspilerConfig,
                         memory_optimize, release_memory)
from . import lod_tensor as lod_tensor_mod
from .lod_tensor import (LoDTensor, create_lod_tensor,
                         create_random_int_lodtensor)
from .framework.compiler import make_mesh
from .layers.io import data
from .install_check import run_check

__version__ = "0.1.0"


def cuda_places(device_ids=None):
    """API-compat shim: on TPU builds, 'accelerator places' are TPU chips."""
    import jax
    n = len(jax.devices())
    ids = range(n) if device_ids is None else device_ids
    return [TPUPlace(i) for i in ids]


def tpu_places(device_ids=None):
    import jax
    n = len(jax.devices())
    ids = range(n) if device_ids is None else device_ids
    return [TPUPlace(i) for i in ids]


def cpu_places(device_count=None):
    return [CPUPlace()]
