"""DistributeTranspiler
(ref python/paddle/fluid/transpiler/distribute_transpiler.py).

The reference rewrites a single-process Program into a trainer half
(send/recv ops to pservers) or, in collective mode, inserts NCCL
allreduce ops.  On a TPU pod the equivalent machinery is the Mesh +
pjit path (distributed/mesh.py, framework/compiler.py): parameters get
NamedShardings and XLA inserts the collectives over ICI.  This adapter
keeps the fluid call sequence working:

    t = DistributeTranspiler(config)
    t.transpile(trainer_id, trainers=N, pservers=..., program=prog)
    train_prog = t.get_trainer_program()     # mesh-annotated, same IR

``get_pserver_program`` raises with guidance: there is no pserver
process on a TPU pod; sparse tables live as row-sharded mesh state
(distributed/sharded_embedding.py) — the documented design decision in
SURVEY §2.7.
"""
from ..framework import program as program_mod
from ..distributed import mesh as mesh_mod
from .ps_dispatcher import RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]


class DistributeTranspilerConfig(object):
    """Knobs of the reference transpiler (ref :134).  slice_var_up /
    min_block_size governed pserver block slicing; on the mesh they map
    to whether large embedding tables are row-sharded ("dp" rows)."""

    slice_var_up = True
    split_method = RoundRobin
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "collective"  # TPU default: collective data-parallel
    print_log = False
    wait_port = True

    def __init__(self):
        pass


class DistributeTranspiler(object):
    """Configure a Program for multi-device/multi-host execution
    (ref :243).  ``transpile`` installs/validates the dp mesh and
    annotates distributed lookup tables; the Program IR is unchanged —
    partitioning happens at jit time in CompiledProgram."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        if self.config.split_method is None:
            self.config.split_method = RoundRobin
        assert self.config.min_block_size >= 8192
        assert self.config.split_method.__name__ in ["RoundRobin",
                                                     "HashName"]
        self._transpiled = False

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        """Record the job layout and install a dp mesh sized to
        ``trainers`` when none is active (ref :522)."""
        if program is None:
            program = program_mod.default_main_program()
        if not sync_mode:
            raise NotImplementedError(
                "async (pserver) mode is N/A on TPU pods: geo-async "
                "rounds exist to hide commodity-network latency; over "
                "ICI, synchronous dp with XLA collectives is strictly "
                "faster (SURVEY design decisions)")
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program
        self.startup_program = (startup_program or
                                program_mod.default_startup_program())
        self.pserver_endpoints = [e.strip() for e in pservers.split(",")
                                  if e.strip()]
        if mesh_mod.get_mesh() is None and trainers > 1:
            import jax
            if len(jax.devices()) >= trainers:
                mesh_mod.init_mesh({"dp": trainers})
            # else: single-process build of a multi-host job — the mesh
            # is installed at launch time (distributed.launch / fleet.init)
            # where all hosts' devices are visible
        # annotate distributed lookup tables for row-sharding, the
        # pserver-block equivalent (slice_var_up)
        if self.config.slice_var_up:
            for var in program.global_block().all_parameters():
                if getattr(var, "is_distributed", False):
                    var.sharding = ("dp",) + (None,) * (len(var.shape) - 1)
        self._transpiled = True

    def get_trainer_program(self, wait_port=True):
        """The trainer-side Program (ref :961).  Same IR object —
        sharding annotations are carried on its vars; run it through
        CompiledProgram to execute SPMD."""
        if not self._transpiled:
            raise RuntimeError("call transpile() first")
        return self.origin_program

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Trainer startup Program (ref :1398)."""
        if not self._transpiled:
            raise RuntimeError("call transpile() first")
        return self.startup_program

    def _no_pserver(self):
        raise NotImplementedError(
            "no pserver process exists on a TPU pod: sparse tables are "
            "row-sharded mesh state (paddle_tpu.distributed."
            "sharded_embedding); dense sync happens inside the jitted "
            "step via XLA collectives. Port pserver jobs by dropping "
            "the pserver launch and running the trainer program under "
            "CompiledProgram with a dp mesh.")

    def get_pserver_program(self, endpoint):  # ref :1096
        self._no_pserver()

    def get_pserver_programs(self, endpoint):  # ref :1367
        self._no_pserver()
