"""Parameter-server shard dispatchers
(ref python/paddle/fluid/transpiler/ps_dispatcher.py).

Used by the reference to decide which pserver endpoint owns each
parameter shard.  Kept intact because the same policy question exists
on TPU — which mesh row owns which row-shard of a distributed embedding
(distributed/sharded_embedding.py) — and fluid scripts construct these
classes directly.
"""

__all__ = ["PSDispatcher", "HashName", "RoundRobin"]


class PSDispatcher(object):
    """Base: dispatch a list of vars onto endpoints (ref :18)."""

    def __init__(self, pserver_endpoints):
        self._eps = pserver_endpoints
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError("Interface has not been implemented.")


class HashName(PSDispatcher):
    """Hash each var's name onto an endpoint (ref :49) — deterministic
    across restarts, the property checkpoints rely on."""

    def __init__(self, pserver_endpoints):
        super(HashName, self).__init__(pserver_endpoints)

    def _hash_block(self, block_str, total):
        return hash(block_str) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server_id = self._hash_block(var.name(), len(self._eps))
            eplist.append(self._eps[server_id])
        return eplist


class RoundRobin(PSDispatcher):
    """Cycle through endpoints in order (ref :88)."""

    def __init__(self, pserver_endpoints):
        super(RoundRobin, self).__init__(pserver_endpoints)

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server_for_param = self._eps[self._step]
            eplist.append(server_for_param)
            self._step += 1
            if self._step >= len(self._eps):
                self._step = 0
        return eplist
