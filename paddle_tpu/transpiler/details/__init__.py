"""fluid.transpiler.details parity (ref transpiler/details/): program
manipulation helpers."""
from .program_utils import delete_ops, find_op_by_input_arg, \
    find_op_by_output_arg  # noqa: F401

__all__ = ["delete_ops", "find_op_by_input_arg", "find_op_by_output_arg"]
