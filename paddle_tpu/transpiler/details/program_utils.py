"""ref transpiler/details/program_utils.py — the helpers the
transpilers use to edit Program IR, against our Block/Operator."""

__all__ = ["delete_ops", "find_op_by_input_arg", "find_op_by_output_arg"]


def delete_ops(block, ops):
    doomed = {id(op) for op in ops}
    block.ops[:] = [op for op in block.ops if id(op) not in doomed]
    block.program._version += 1


def find_op_by_input_arg(block, arg_name):
    # Operator.input_names() flattens to VAR names; slot iteration needs
    # the .inputs dict keys
    for index, op in enumerate(block.ops):
        for slot in op.inputs:
            if arg_name in op.input(slot):
                return index
    return -1


def find_op_by_output_arg(block, arg_name, reverse=False):
    ops = list(enumerate(block.ops))
    if reverse:
        ops = reversed(ops)
    for index, op in ops:
        for slot in op.outputs:
            if arg_name in op.output(slot):
                return index
    return -1
