"""Distributed-variable descriptors (ref transpiler/details/
vars_distributed.py): bookkeeping for how one logical variable is split
into per-shard blocks. The mesh runtime shards via NamedSharding, so
these descriptors serve porting/introspection of transpiler-era plans.
"""

__all__ = ["VarStruct", "VarDistributed", "VarsDistributed"]


class VarStruct(object):
    """Static description of one variable (name/shape/dtype/lod/persist)."""

    def __init__(self, name, shape, dtype, type=None, lod_level=0,
                 persistable=False):
        self.name = name
        self.shape = tuple(shape or ())
        self.dtype = dtype
        self.type = type
        self.lod_level = lod_level
        self.persistable = persistable

    def __repr__(self):
        return "VarStruct(%s, shape=%s, dtype=%s)" % (
            self.name, self.shape, self.dtype)


class VarDistributed(object):
    """One shard of an origin variable: its slice geometry + placement."""

    def __init__(self, origin_var, slice_var, is_slice=None, block_id=None,
                 offset=None, vtype=None, endpoint=None):
        self.origin_var = origin_var
        self.slice_var = slice_var
        self.is_slice = bool(is_slice)
        self.block_id = block_id
        self.offset = offset
        self.vtype = vtype
        self.endpoint = endpoint

    @staticmethod
    def equal(var1, var2):
        return (var1.name == var2.name and var1.shape == var2.shape
                and str(var1.dtype) == str(var2.dtype)
                and var1.lod_level == var2.lod_level
                and var1.persistable == var2.persistable)

    def __repr__(self):
        return "VarDistributed(%s -> %s @%s)" % (
            getattr(self.origin_var, "name", self.origin_var),
            getattr(self.slice_var, "name", self.slice_var),
            self.endpoint)


class VarsDistributed(object):
    """Registry of VarDistributed entries keyed by slice-var name."""

    def __init__(self):
        self.distributed_vars = {}

    def add_distributed_var(self, origin_var, slice_var, is_slice=None,
                            block_id=None, offset=None, vtype=None,
                            endpoint=None):
        v = VarDistributed(origin_var, slice_var, is_slice, block_id,
                           offset, vtype, endpoint)
        self.distributed_vars[getattr(slice_var, "name", slice_var)] = v
        return v

    def get_distributed_var_by_slice(self, name):
        return self.distributed_vars.get(name)

    def get_distributed_var_by_origin_and_ep(self, origin_name, endpoint):
        for v in self.distributed_vars.values():
            if getattr(v.origin_var, "name", v.origin_var) == origin_name \
                    and v.endpoint == endpoint:
                return v
        return None
