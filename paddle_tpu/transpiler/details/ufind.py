"""Union-find (ref transpiler/details/ufind.py) — used by the reference
transpiler to group variables that must co-locate; generally useful for
partition planning."""

__all__ = ["UnionFind"]


class UnionFind(object):
    """Union-find over an initial element list; elements hashable."""

    def __init__(self, elements=None):
        self._parent = {}
        for e in elements or ():
            self._parent.setdefault(e, e)

    def _root(self, x):
        self._parent.setdefault(x, x)
        while self._parent[x] != x:
            self._parent[x] = self._parent[self._parent[x]]
            x = self._parent[x]
        return x

    def union(self, a, b):
        ra, rb = self._root(a), self._root(b)
        if ra != rb:
            self._parent[rb] = ra

    def is_connected(self, a, b):
        return self._root(a) == self._root(b)

    def find(self, x):
        return self._root(x)
