"""Port-liveness wait (ref transpiler/details/checkport.py): block until
every "ip:port" endpoint accepts a TCP connection — the reference used
it to gate trainers on pserver startup; useful here to gate multi-host
jax.distributed jobs on the coordinator."""
import socket
import time

__all__ = ["wait_server_ready"]


def wait_server_ready(endpoints, timeout_s=300.0, poll_s=1.0):
    deadline = time.time() + timeout_s
    pending = list(endpoints)
    while pending:
        if time.time() > deadline:
            raise TimeoutError(
                "servers not ready within %.0fs: %s"
                % (timeout_s, ", ".join(pending)))
        nxt = []
        for ep in pending:
            host, _, port = ep.rpartition(":")
            try:
                with socket.create_connection((host, int(port)),
                                              timeout=poll_s):
                    pass
            except OSError:
                nxt.append(ep)
        pending = nxt
        if pending:
            time.sleep(poll_s)
