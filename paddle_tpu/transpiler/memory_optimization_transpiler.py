"""Memory-optimization transpiler
(ref python/paddle/fluid/transpiler/memory_optimization_transpiler.py).

The reference walks op liveness and renames dead vars so buffers get
reused.  Under XLA that rewrite is actively harmful — the compiler's
own buffer-assignment pass performs liveness-based reuse on the fused
HLO, and donation (Executor's donate_argnums on parameters) already
gives in-place updates.  These functions therefore validate their
arguments, stamp the request on the Program (so BuildStrategy /
CompiledProgram can surface it), and leave the graph byte-identical.
"""
from ..framework import program as program_mod

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """Record a buffer-reuse request on the Program (ref :18).

    XLA's buffer assignment subsumes the reference's in-graph renaming;
    the flag is kept so CompiledProgram can assert the memory strategy
    was requested (parity with BuildStrategy.memory_optimize).
    """
    if level != 0 and level != 1:
        raise ValueError("only level 0 and level 1 are supported")
    if not isinstance(input_program, program_mod.Program):
        raise TypeError("memory_optimize expects a Program, got %s" %
                        type(input_program))
    input_program._memory_optimize_requested = True
    input_program._memory_optimize_skip = set(skip_opt_set or ())
    if print_log:
        print("memory_optimize: delegated to XLA buffer assignment "
              "(donated params + liveness reuse inside the fused step)")
    return input_program


def release_memory(input_program, skip_opt_set=None):
    """Early-delete pass (ref :42) — subsumed by XLA liveness; kept as a
    validated no-op for script parity."""
    if not isinstance(input_program, program_mod.Program):
        raise TypeError("release_memory expects a Program, got %s" %
                        type(input_program))
    input_program._release_memory_requested = True
    return input_program
