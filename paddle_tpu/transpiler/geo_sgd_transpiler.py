"""fluid.transpiler.geo_sgd_transpiler (ref
transpiler/geo_sgd_transpiler.py): GEO async-SGD exists to hide slow
networks; N/A on ICI (see PORTING.md). Raises with guidance."""

__all__ = ["GeoSgdTranspiler"]


class GeoSgdTranspiler(object):
    def __init__(self, config=None):
        raise NotImplementedError(
            "GEO async-SGD is N/A on TPU pods: synchronous dp over ICI "
            "(CompiledProgram/fleet with a mesh) replaces it. See "
            "PORTING.md 'Capability substitutions'.")
