"""fluid.transpiler.collective parity (ref transpiler/collective.py:
Collective/GradAllReduce/LocalSGD rewrite programs to insert NCCL
allreduce). TPU-native: XLA inserts the collectives from mesh
shardings, so transpile() installs the mesh and leaves the program
whole — run it under CompiledProgram/fleet as usual."""
from ..distributed import mesh as _mesh_mod

__all__ = ["Collective", "GradAllReduce", "LocalSGD"]


class Collective(object):
    def __init__(self, nrings=1):
        self.nrings = nrings

    def transpile(self, startup_program=None, main_program=None, rank=0,
                  endpoints="127.0.0.1:6174", current_endpoint=None,
                  wait_port=True):
        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        self.nranks = len(endpoints)
        self.rank = rank
        if _mesh_mod.get_mesh() is None:
            # the standard data-parallel mesh over ALL devices — the
            # same global mesh on every process (endpoint count is a
            # process-topology detail NCCL needed; XLA's mesh spans the
            # whole job)
            import jax
            _mesh_mod.init_mesh({"dp": len(jax.devices())})


class GradAllReduce(Collective):
    """Dense allreduce of gradients — what pjit emits from dp shardings."""


class LocalSGD(Collective):
    """Reference LocalSGD averages params every k steps to cut comms; on
    ICI the dense allreduce is cheap enough that per-step sync dp is the
    installed behavior (documented substitution)."""
