"""Transpiler package (ref python/paddle/fluid/transpiler/__init__.py).

On the reference, transpilers REWRITE the Program: DistributeTranspiler
splits it into trainer/pserver halves wired with send/recv ops, and
memory_optimization_transpiler renames vars to reuse buffers.  On TPU
both jobs belong to the compiler stack — SPMD partitioning to pjit over
the Mesh, buffer liveness to XLA — so this package keeps the fluid API
as a thin, *honest* adapter: DistributeTranspiler configures the mesh
data-parallel path and returns the same Program; memory_optimize is a
documented no-op that records the request for the executor's donation /
remat machinery.
"""
from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig)
from .memory_optimization_transpiler import memory_optimize, release_memory
from .ps_dispatcher import HashName, RoundRobin, PSDispatcher

__all__ = [
    "DistributeTranspiler", "DistributeTranspilerConfig",
    "memory_optimize", "release_memory",
    "HashName", "RoundRobin", "PSDispatcher",
]
