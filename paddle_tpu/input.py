"""Module-path alias for fluid.input (ref python/paddle/fluid/input.py:
one_hot + embedding at the package level)."""
from .layers.nn import embedding, one_hot  # noqa: F401

__all__ = ["one_hot", "embedding"]
