"""Pragmatic stand-in for fluid.core (the reference's C++ pybind module,
ref paddle/fluid/pybind/pybind.cc). Scripts that reach into core for
places or scopes port unchanged; kernel-level internals have no TPU
counterpart (XLA owns them)."""
from .framework.place import CPUPlace, TPUPlace  # noqa: F401
from .framework.scope import Scope  # noqa: F401
from .lod_tensor import LoDTensor  # noqa: F401


class LoDTensorArray(list):
    """reference core.LoDTensorArray: a growable vector of LoDTensors."""
    def append(self, t):
        list.append(self, t)

# scripts written for the reference name CUDA places; on TPU they map to
# the accelerator place (matching paddle_tpu.cuda_places() behavior)
CUDAPlace = TPUPlace
CUDAPinnedPlace = CPUPlace


def is_compiled_with_cuda():
    return False


def get_cuda_device_count():
    return 0


__all__ = ["CPUPlace", "TPUPlace", "CUDAPlace", "CUDAPinnedPlace",
           "Scope", "LoDTensor", "LoDTensorArray",
           "is_compiled_with_cuda", "get_cuda_device_count"]
