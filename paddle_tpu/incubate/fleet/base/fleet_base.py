"""Fleet abstract base (ref incubate/fleet/base/fleet_base.py): the
interface both the collective fleet (our mesh-backed
distributed/fleet.py singleton) and the pserver fleet implement."""
import abc

from ....distributed import fleet as _impl
from ....distributed.fleet import DistributedOptimizer  # noqa: F401

__all__ = ["Mode", "Fleet", "DistributedOptimizer", "fleet"]


class Mode(object):
    """Training-architecture constants (ref fleet_base.Mode)."""
    TRANSPILER = 1
    PSLIB = 2
    COLLECTIVE = 3


class Fleet(object, metaclass=abc.ABCMeta):
    """Abstract fleet interface. The concrete TPU implementation is the
    collective fleet in distributed/fleet.py — a mesh + XLA collectives
    (Mode.COLLECTIVE); pserver modes are N/A on TPU (PORTING.md)."""

    def __init__(self, mode=Mode.COLLECTIVE):
        self._mode = mode

    def is_first_worker(self):
        return _impl.is_first_worker()

    def worker_index(self):
        return _impl.worker_index()

    def worker_num(self):
        return _impl.worker_num()

    @abc.abstractmethod
    def init_worker(self):
        raise NotImplementedError

    @abc.abstractmethod
    def run_worker(self, main_programs=None, scopes=None):
        raise NotImplementedError

    @abc.abstractmethod
    def init_server(self, model_dir=None):
        raise NotImplementedError

    @abc.abstractmethod
    def run_server(self):
        raise NotImplementedError

    @abc.abstractmethod
    def stop_worker(self):
        raise NotImplementedError

    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        raise NotImplementedError


# the working singleton users actually call (collective mode)
fleet = _impl
