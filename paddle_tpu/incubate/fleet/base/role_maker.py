"""fluid.incubate.fleet.base.role_maker parity (ref
incubate/fleet/base/role_maker.py): rank/size discovery under
jax.distributed."""
from ....distributed.fleet import PaddleCloudRoleMaker  # noqa: F401

__all__ = ["PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit rank/size (reference UserDefinedRoleMaker) — on TPU the
    runtime already knows both; arguments are validated and recorded."""

    def __init__(self, current_id=0, role=None, worker_num=0,
                 server_endpoints=None):
        super(UserDefinedRoleMaker, self).__init__(is_collective=True)
        self._current_id = int(current_id)
        self._worker_num = int(worker_num)

    def worker_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num or super(UserDefinedRoleMaker,
                                         self).worker_num()

    def is_first_worker(self):
        return self.worker_index() == 0
