"""ref incubate/fleet/base/."""
from . import role_maker  # noqa: F401
