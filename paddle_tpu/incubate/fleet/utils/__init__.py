"""ref incubate/fleet/utils/."""
from . import fleet_util, fleet_barrier_util, hdfs  # noqa: F401
