"""ref incubate/fleet/utils/fleet_barrier_util.py: check_all_trainers_
ready barriers the job (pserver table tricks in the reference; a device
barrier here)."""

__all__ = ["check_all_trainers_ready"]


def check_all_trainers_ready(input_var_name=None, timeout=None):
    import jax
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils  # pragma: no cover
    multihost_utils.sync_global_devices(  # pragma: no cover
        "fleet_barrier_%s" % (input_var_name or "default"))
