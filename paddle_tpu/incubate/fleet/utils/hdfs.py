"""ref incubate/fleet/utils/hdfs.py — same N/A story as
contrib.utils.hdfs_utils (POSIX-visible mounts replace HDFS staging)."""
from ....contrib.utils.hdfs_utils import HDFSClient, multi_download, \
    multi_upload  # noqa: F401

__all__ = ["HDFSClient", "multi_download", "multi_upload"]
