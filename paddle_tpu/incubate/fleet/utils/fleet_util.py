"""ref incubate/fleet/utils/fleet_util.py: rank helpers + all-reduce of
host metrics (the reference goes through the pserver barrier; here XLA
collectives / multihost utils)."""
import numpy as np

__all__ = ["FleetUtil"]


class FleetUtil(object):
    def rank0_print(self, s):
        import jax
        if jax.process_index() == 0:
            print(s, flush=True)

    def all_reduce(self, value, op="sum"):
        """Reduce a host scalar/array across processes."""
        import jax
        arr = np.asarray(value, np.float64)
        if jax.process_count() == 1:
            return arr
        from jax.experimental import multihost_utils  # pragma: no cover
        out = multihost_utils.process_allgather(arr)  # pragma: no cover
        if op == "sum":  # pragma: no cover
            return out.sum(axis=0)
        if op == "max":  # pragma: no cover
            return out.max(axis=0)
        if op == "min":  # pragma: no cover
            return out.min(axis=0)
        raise ValueError("unsupported op %r" % op)  # pragma: no cover
