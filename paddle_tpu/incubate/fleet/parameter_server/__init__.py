"""fluid.incubate.fleet.parameter_server (ref
incubate/fleet/parameter_server/): pserver processes are N/A on TPU —
sparse tables are row-sharded mesh state (distributed/
sharded_embedding.py, PORTING.md 'Capability substitutions')."""
from . import pslib  # noqa: F401
