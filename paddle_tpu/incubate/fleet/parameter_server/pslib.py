"""PSLib downpour surface (ref
incubate/fleet/parameter_server/pslib/__init__.py:28): configures
Baidu's proprietary parameter-server binary. N/A here; the capability
(huge sparse tables, async updates) maps to row-sharded embeddings over
the mesh."""

__all__ = ["fleet"]

_MSG = ("PSLib/Downpour is N/A on TPU: use layers.embedding("
        "is_distributed=True) / distributed.sharded_embedding for "
        "row-sharded tables over the mesh (PORTING.md).")


class _PSLibStub(object):
    def __getattr__(self, name):
        raise NotImplementedError(_MSG)


fleet = _PSLibStub()
