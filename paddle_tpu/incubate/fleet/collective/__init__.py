"""fluid.incubate.fleet.collective parity (ref
incubate/fleet/collective/__init__.py): `fleet` object + strategy."""
from ....distributed import fleet  # noqa: F401
from ....distributed.mesh import DistributedStrategy  # noqa: F401
from ....distributed.fleet import DistributedOptimizer  # noqa: F401

__all__ = ["fleet", "DistributedStrategy", "DistributedOptimizer"]
