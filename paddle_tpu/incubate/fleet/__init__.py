"""fluid.incubate.fleet parity (ref incubate/fleet/): the collective
fleet API lives in distributed/fleet.py; base/collective/
parameter_server mirror the reference package layout."""
from ...distributed import fleet as _fleet_mod
from ...distributed.fleet import (init, worker_index, worker_num,  # noqa: F401
                                  is_first_worker, distributed_optimizer,
                                  DistributedOptimizer,
                                  PaddleCloudRoleMaker,
                                  main_program_compiled)

# module alias: `from paddle_tpu.incubate import fleet; fleet.init(...)`
fleet = _fleet_mod
