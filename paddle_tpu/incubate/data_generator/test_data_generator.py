"""Demo generators (ref incubate/data_generator/test_data_generator.py):
the reference ships a tiny runnable example of both generator flavors;
kept for parity and as living documentation of the slot text format."""
from . import MultiSlotDataGenerator, MultiSlotStringDataGenerator

__all__ = ["SyntheticData", "SyntheticStringData"]


class SyntheticData(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def data_iter():
            for i in range(10000):
                yield [("words", [1, 2, 3, 4]), ("label", [0])]

        return data_iter


class SyntheticStringData(MultiSlotStringDataGenerator):
    def generate_sample(self, line):
        def data_iter():
            for i in range(10000):
                yield [("words", ["1", "2", "3", "4"]),
                       ("label", ["0"])]

        return data_iter


if __name__ == "__main__":  # pragma: no cover - manual demo
    sd = SyntheticData()
    sd._set_line_limit(5)
    sd.run_from_memory()
