"""User-defined data generators for Dataset pipelines
(ref python/paddle/fluid/incubate/data_generator/__init__.py).

Subclass DataGenerator / MultiSlotDataGenerator, implement
``generate_sample(line)``, and the generator renders slot-formatted
text lines consumable by the Dataset API's record plane
(paddle_tpu/dataset/dataset_api.py).  The slot text format is the
reference's: ``<slot_len> v0 v1 ... per slot``, space-joined.
"""
import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator(object):
    """Base class (ref :21): drive lines through generate_sample /
    generate_batch and emit slot text to stdout (the Dataset feeds the
    emitted stream to its readers)."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int):
            raise ValueError("line_limit%s must be in int type" %
                             type(line_limit))
        if line_limit < 1:
            raise ValueError("line_limit can not less than 1")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def _emit(self, sample, write):
        write(self._gen_str(sample))

    def run_from_memory(self, write=None):
        """Generate from memory (ref :66); ``write`` defaults to
        sys.stdout.write — pass a collector for in-process use."""
        write = write or sys.stdout.write
        batch_samples = []
        line_iter = self.generate_sample(None)
        for parsed in line_iter():
            if parsed is None:
                continue
            batch_samples.append(parsed)
            if len(batch_samples) == self.batch_size_:
                for sample in self.generate_batch(batch_samples)():
                    self._emit(sample, write)
                batch_samples = []
        if batch_samples:
            for sample in self.generate_batch(batch_samples)():
                self._emit(sample, write)

    def run_from_stdin(self, read=None, write=None):
        """Parse lines from stdin and emit slot text (ref :100)."""
        read = read or sys.stdin
        write = write or sys.stdout.write
        batch_samples = []
        for line in read:
            line_iter = self.generate_sample(line)
            for parsed in line_iter():
                if parsed is None:
                    continue
                batch_samples.append(parsed)
                if len(batch_samples) == self.batch_size_:
                    for sample in self.generate_batch(batch_samples)():
                        self._emit(sample, write)
                    batch_samples = []
        if batch_samples:
            for sample in self.generate_batch(batch_samples)():
                self._emit(sample, write)

    def _gen_str(self, line):
        raise NotImplementedError(
            "Please inherit MultiSlotDataGenerator or "
            "MultiSlotStringDataGenerator to implement _gen_str")

    def generate_sample(self, line):
        raise NotImplementedError(
            "Please rewrite this function to return a list or tuple: " +
            "[(name, [feasign, ...]), ...] or ((name, [feasign, ...]), ...)")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample

        return local_iter


def _check_slots(line):
    if not isinstance(line, (list, tuple)):
        raise ValueError(
            "the output of process() must be in list or tuple type")
    for item in line:
        if not isinstance(item, (list, tuple)) or len(item) != 2:
            raise ValueError("each slot must be a (name, values) pair")
        name, elements = item
        if not isinstance(name, str):
            raise ValueError("the slot name %r is not a string" % (name,))
        if not isinstance(elements, (list, tuple)) or not elements:
            raise ValueError("slot %s must carry a non-empty value list" %
                             name)


class MultiSlotStringDataGenerator(DataGenerator):
    """Slots of raw strings (ref :241): text line =
    "len v0 v1 ... len v0 ..." per slot, space-joined."""

    def _gen_str(self, line):
        _check_slots(line)
        output = ""
        for item in line:
            name, elements = item
            if output:
                output += " "
            out_str = [str(len(elements))]
            out_str.extend(str(e) for e in elements)
            output += " ".join(out_str)
        return output + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Slots of ints/floats (ref :282), with per-slot type checking —
    a slot must stay int or float across all emitted samples."""

    def _gen_str(self, line):
        _check_slots(line)
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                slot_type = "uint64"
                for e in elements:
                    if isinstance(e, float):
                        slot_type = "float"
                    elif not isinstance(e, int):
                        raise ValueError(
                            "the value of slot %s must be int or float" %
                            name)
                self._proto_info.append((name, slot_type))
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    "the complete field set of two given line are "
                    "inconsistent.")
            for i, (name, elements) in enumerate(line):
                if name != self._proto_info[i][0]:
                    raise ValueError(
                        "the complete field set of two given line are not "
                        "exactly the same.")
                if self._proto_info[i][1] != "float":
                    for e in elements:
                        if isinstance(e, float):
                            self._proto_info[i] = (name, "float")
                        elif not isinstance(e, int):
                            raise ValueError(
                                "the value of slot %s must be int or "
                                "float" % name)
        output = ""
        for name, elements in line:
            if output:
                output += " "
            out_str = [str(len(elements))]
            out_str.extend(str(e) for e in elements)
            output += " ".join(out_str)
        return output + "\n"
