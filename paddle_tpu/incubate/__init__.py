"""Incubating APIs (ref python/paddle/fluid/incubate/__init__.py):
fleet lives in paddle_tpu.distributed.fleet (re-exported here for the
reference import path ``incubate.fleet``), plus data_generator."""
from . import data_generator
from ..distributed import fleet

__all__ = ["data_generator", "fleet"]
