"""Incubating APIs (ref python/paddle/fluid/incubate/__init__.py):
the fleet subpackage mirrors the reference layout (base/collective/
parameter_server) over paddle_tpu.distributed.fleet, plus data_generator."""
from . import data_generator
from . import fleet

__all__ = ["data_generator", "fleet"]
