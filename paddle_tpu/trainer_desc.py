"""Module-path alias for fluid.trainer_desc (ref
python/paddle/fluid/trainer_desc.py)."""
from .trainer_factory import TrainerDesc, MultiTrainer, \
    DistMultiTrainer  # noqa: F401

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer"]
