"""CRNN-CTC text recognition (reference: PaddlePaddle/models
ocr_recognition — crnn_ctc_model.py).

Conv feature extractor -> columns-as-timesteps -> bidirectional GRU ->
per-step vocab logits -> warpctc loss; greedy CTC decode for
inference.  Exercises the conv stack, the scan-based RNNs and the CTC
kernel (ops/crf_ops.py warpctc) in one model.
"""
import numpy as np

from .. import layers
from ..contrib.layers import basic_gru
from ..framework.program import Program, program_guard

__all__ = ["crnn_ctc_program", "synthetic_ocr_batch", "ctc_greedy_decode"]


def _conv_pool(x, filters, is_test=False):
    y = layers.conv2d(x, num_filters=filters, filter_size=3, padding=1,
                      bias_attr=False)
    y = layers.batch_norm(y, act="relu", is_test=is_test)
    # pool height only after the first stages, keeping width = time
    return layers.pool2d(y, pool_size=[2, 1], pool_stride=[2, 1],
                         pool_type="max")


def crnn_ctc_program(num_classes=36, image_shape=(1, 32, 64),
                     hidden=64, max_label=16, optimizer_fn=None,
                     is_test=False):
    """(main, startup, feeds, fetches): fetches carry 'loss' (CTC) and
    'logits' (T, N, num_classes+1; blank = num_classes)."""
    c, h, w = image_shape
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("image", [c, h, w], "float32")
        label = layers.data("label", [max_label], "int32")
        label_len = layers.data("label_len", [1], "int64")
        y = _conv_pool(img, 32, is_test)      # h/2
        y = _conv_pool(y, 64, is_test)        # h/4
        y = _conv_pool(y, 128, is_test)       # h/8
        # (N, C, H', W) -> time-major columns (N, W, C*H')
        n_, ch, hh = y.shape[0], y.shape[1], y.shape[2]
        y = layers.transpose(y, perm=[0, 3, 1, 2])
        feat = layers.reshape(y, [-1, w, ch * hh])
        rnn_out, _ = basic_gru(feat, None, hidden_size=hidden,
                               bidirectional=True)
        logits = layers.fc(rnn_out, size=num_classes + 1,
                           num_flatten_dims=2)
        logits_tm = layers.transpose(logits, perm=[1, 0, 2])  # (T, N, C)
        t = w
        in_len = layers.fill_constant_batch_size_like(
            label_len, shape=[-1], dtype="int64", value=t)
        loss = layers.reduce_mean(layers.warpctc(
            logits_tm, label, blank=num_classes,
            input_length=in_len, label_length=layers.reshape(label_len,
                                                             [-1])))
        if optimizer_fn is not None:
            optimizer_fn(loss)
    # dce allowlist (found by the PR 14 verifier): the bidirectional
    # rnn emits last-state slice/squeeze/stack ops the CTC head never
    # consumes — dead by API shape, XLA DCEs them at trace, and the
    # report would flag them on every compile.
    from ..framework import analysis as _analysis
    _analysis.allowlist(main, _analysis.PASS_DCE,
                        reason="rnn last-state chain unused by the "
                               "CTC head")
    return main, startup, \
        {"image": img, "label": label, "label_len": label_len}, \
        {"loss": loss, "logits": logits_tm}


def ctc_greedy_decode(logits_tm, blank):
    """Host-side greedy CTC collapse of (T, N, C) logits -> list of
    label lists (merge repeats, drop blanks)."""
    ids = np.argmax(np.asarray(logits_tm), axis=-1)  # (T, N)
    outs = []
    for n in range(ids.shape[1]):
        seq, prev = [], -1
        for t in range(ids.shape[0]):
            k = int(ids[t, n])
            if k != prev and k != blank:
                seq.append(k)
            prev = k
        outs.append(seq)
    return outs


def synthetic_ocr_batch(batch, image_shape=(1, 32, 64), num_classes=36,
                        max_label=16, seed=0):
    """Images whose column intensity encodes the label sequence, so the
    model has real signal to fit."""
    rng = np.random.RandomState(seed)
    c, h, w = image_shape
    imgs = rng.rand(batch, c, h, w).astype(np.float32) * 0.1
    labels = np.zeros((batch, max_label), np.int32)
    lens = np.zeros((batch, 1), np.int64)
    for b in range(batch):
        n = rng.randint(2, max_label // 2)
        lab = rng.randint(0, num_classes, n)
        labels[b, :n] = lab
        lens[b, 0] = n
        # paint each glyph as a vertical band with class-keyed intensity
        band = w // max(n, 1)
        for i, k in enumerate(lab):
            imgs[b, :, :, i * band:(i + 1) * band] += \
                (k + 1) / float(num_classes + 1)
    return {"image": imgs, "label": labels, "label_len": lens}
