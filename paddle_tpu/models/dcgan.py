"""DCGAN (static graph) — adversarial training in one fused step.

Reference analogue: the fluid book/models-repo dc_gan example (separate
generator/discriminator programs alternated from Python). TPU-first
design: ONE program computes both losses and applies BOTH optimizers
via ``minimize(parameter_list=...)`` scoping (simultaneous GAN
updates) — the whole adversarial step is a single XLA computation, so
there is no per-phase dispatch or parameter ping-pong between host
calls. Discriminator weights are shared across the real/fake branches
by explicit parameter names; append_backward sums their gradients.
"""
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.initializer import NormalInitializer


class DCGANConfig(object):
    def __init__(self, noise_dim=64, base_channels=32, image_size=32,
                 image_channels=1, dtype="float32"):
        assert image_size % 4 == 0
        self.noise_dim = noise_dim
        self.base_channels = base_channels
        self.image_size = image_size
        self.image_channels = image_channels
        self.dtype = dtype


def _attr(name):
    return ParamAttr(name=name, initializer=NormalInitializer(scale=0.02))


def generator(z, cfg, name="gen", is_test=False):
    """(N, noise_dim) -> (N, C, S, S) in [-1, 1]."""
    s4 = cfg.image_size // 4
    c = cfg.base_channels
    h = layers.fc(z, c * 2 * s4 * s4,
                  param_attr=_attr(name + "_fc.w_0"),
                  bias_attr=ParamAttr(name=name + "_fc.b_0"))
    h = layers.reshape(h, [-1, c * 2, s4, s4])
    h = layers.batch_norm(h, act="relu", is_test=is_test,
                          param_attr=ParamAttr(name=name + "_bn0_s"),
                          bias_attr=ParamAttr(name=name + "_bn0_b"),
                          moving_mean_name=name + "_bn0_m",
                          moving_variance_name=name + "_bn0_v")
    h = layers.conv2d_transpose(
        h, c, filter_size=4, stride=2, padding=1,
        param_attr=_attr(name + "_dc1.w_0"),
        bias_attr=ParamAttr(name=name + "_dc1.b_0"))
    h = layers.batch_norm(h, act="relu", is_test=is_test,
                          param_attr=ParamAttr(name=name + "_bn1_s"),
                          bias_attr=ParamAttr(name=name + "_bn1_b"),
                          moving_mean_name=name + "_bn1_m",
                          moving_variance_name=name + "_bn1_v")
    h = layers.conv2d_transpose(
        h, cfg.image_channels, filter_size=4, stride=2, padding=1,
        param_attr=_attr(name + "_dc2.w_0"),
        bias_attr=ParamAttr(name=name + "_dc2.b_0"))
    return layers.tanh(h)


def discriminator(img, cfg, name="disc"):
    """(N, C, S, S) -> (N, 1) real/fake logit. Call it on both branches
    with the same ``name`` — weights are shared by parameter name."""
    c = cfg.base_channels
    h = layers.conv2d(img, c, filter_size=4, stride=2, padding=1,
                      param_attr=_attr(name + "_c0.w_0"),
                      bias_attr=ParamAttr(name=name + "_c0.b_0"))
    h = layers.leaky_relu(h, alpha=0.2)
    h = layers.conv2d(h, c * 2, filter_size=4, stride=2, padding=1,
                      param_attr=_attr(name + "_c1.w_0"),
                      bias_attr=ParamAttr(name=name + "_c1.b_0"))
    h = layers.leaky_relu(h, alpha=0.2)
    flat = c * 2 * (cfg.image_size // 4) ** 2
    h = layers.reshape(h, [0, flat])
    return layers.fc(h, 1, param_attr=_attr(name + "_fc.w_0"),
                     bias_attr=ParamAttr(name=name + "_fc.b_0"))


def _bce_logits(logits, target_value):
    t = layers.fill_constant_batch_size_like(logits, logits.shape,
                                             "float32", target_value)
    return layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logits, t))


def dcgan_train_program(cfg, d_lr=2e-4, g_lr=2e-4, beta1=0.5):
    """Build the single adversarial step.

    Feeds: "real" (N,C,S,S) float32 in [-1,1]; "noise" (N,noise_dim).
    Fetches: d_loss, g_loss. Returns (main, startup, feeds, fetch).
    """
    from paddle_tpu import optimizer
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        real = layers.data(
            "real", [cfg.image_channels, cfg.image_size, cfg.image_size],
            dtype="float32")
        noise = layers.data("noise", [cfg.noise_dim], dtype="float32")

        fake = generator(noise, cfg)
        d_real = discriminator(real, cfg)
        d_fake = discriminator(fake, cfg)

        d_loss = layers.elementwise_add(_bce_logits(d_real, 1.0),
                                        _bce_logits(d_fake, 0.0))
        g_loss = _bce_logits(d_fake, 1.0)

        params = main.global_block().all_parameters()
        d_params = [p for p in params if p.name.startswith("disc_")]
        g_params = [p for p in params if p.name.startswith("gen_")]
        optimizer.Adam(d_lr, beta1=beta1).minimize(
            d_loss, parameter_list=d_params)
        optimizer.Adam(g_lr, beta1=beta1).minimize(
            g_loss, parameter_list=g_params)
    return main, startup, ["real", "noise"], {"d_loss": d_loss,
                                              "g_loss": g_loss}


def synthetic_batch(cfg, batch_size, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    real = rng.uniform(-1, 1, (batch_size, cfg.image_channels,
                               cfg.image_size, cfg.image_size))
    noise = rng.randn(batch_size, cfg.noise_dim)
    return {"real": real.astype(np.float32),
            "noise": noise.astype(np.float32)}
