"""BiGRU-CRF sequence labeling (reference: PaddlePaddle/models LAC —
lexical analysis — and the fluid label_semantic_roles book chapter).

Embedding -> stacked bidirectional GRU -> per-token emissions ->
linear_chain_crf training loss, crf_decoding for inference — the
canonical NER/POS/LAC architecture, here on dense (N, T) batches +
length vectors.
"""
import numpy as np

from .. import layers
from ..contrib.layers import basic_gru
from ..framework.program import Program, program_guard

__all__ = ["bigru_crf_program", "synthetic_tagging_batch"]


def bigru_crf_program(vocab_size=1000, num_labels=9, emb_dim=64,
                      hidden=64, num_layers=1, seq_len=32,
                      optimizer_fn=None, crf_lr=1.0):
    """(main, startup, feeds, fetches): fetches carry 'loss' (mean
    negative CRF log-likelihood) and 'decode' (Viterbi paths)."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        words = layers.data("words", [seq_len], "int64")
        targets = layers.data("targets", [seq_len], "int64")
        lens = layers.data("lens", [1], "int64")
        length = layers.reshape(lens, [-1])
        emb = layers.embedding(words, size=[vocab_size, emb_dim])
        rnn_out, _ = basic_gru(emb, None, hidden_size=hidden,
                               num_layers=num_layers, bidirectional=True,
                               sequence_length=length)
        emission = layers.fc(rnn_out, size=num_labels, num_flatten_dims=2)
        from ..param_attr import ParamAttr
        crf_attr = ParamAttr(name="crfw", learning_rate=crf_lr)
        ll = layers.linear_chain_crf(emission, targets,
                                     param_attr=crf_attr, length=length)
        loss = layers.reduce_mean(layers.scale(ll, scale=-1.0))
        decode = layers.crf_decoding(emission,
                                     param_attr=ParamAttr(name="crfw"),
                                     length=length)
        if optimizer_fn is not None:
            optimizer_fn(loss)
    # dce allowlist (found by the PR 14 verifier): basic_gru always
    # emits its last-state gather chain (one_hot-over-time matmul per
    # direction + the final stack) but this head consumes only the
    # per-step emissions — the chain is dead here by API shape, XLA
    # DCEs it at trace, and the report would flag it on every compile.
    from ..framework import analysis as _analysis
    _analysis.allowlist(main, _analysis.PASS_DCE,
                        reason="rnn last-state chain unused by the "
                               "CRF head")
    return main, startup, \
        {"words": words, "targets": targets, "lens": lens}, \
        {"loss": loss, "decode": decode}


def synthetic_tagging_batch(batch, seq_len=32, vocab_size=1000,
                            num_labels=9, seed=0):
    """Deterministic word->label structure (label = word bucket) so the
    tagger can actually fit the mapping in smoke training."""
    rng = np.random.RandomState(seed)
    words = rng.randint(0, vocab_size, (batch, seq_len)).astype(np.int64)
    targets = (words % num_labels).astype(np.int64)
    lens = rng.randint(seq_len // 2, seq_len + 1,
                       (batch, 1)).astype(np.int64)
    return {"words": words, "targets": targets, "lens": lens}
