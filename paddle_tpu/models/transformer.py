"""Transformer-base NMT (WMT en-de config).

Reference parity: PaddlePaddle/models neural_machine_translation/transformer
(BASELINE config). Encoder-decoder with pre-softmax label smoothing and Noam
LR, greedy/beam decode for inference. TPU-first: fused attention ops,
causal masking via the attention kernel (no (T,T) bias materialization),
static shapes throughout.
"""
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.layers.attention import (multi_head_attention,
                                         fused_attention, mha_kv_projection)
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.initializer import XavierInitializer


class TransformerConfig(object):
    def __init__(self, src_vocab=30000, trg_vocab=30000, max_length=256,
                 d_model=512, d_inner=2048, n_head=8, n_layer=6,
                 dropout=0.1, label_smooth_eps=0.1, tp=False):
        self.src_vocab = src_vocab
        self.trg_vocab = trg_vocab
        self.max_length = max_length
        self.d_model = d_model
        self.d_inner = d_inner
        self.n_head = n_head
        self.n_layer = n_layer
        self.dropout = dropout
        self.label_smooth_eps = label_smooth_eps
        self.tp = tp


def _embed(ids, vocab, cfg, name, is_test, pos_offset=0):
    emb = layers.embedding(
        ids, [vocab, cfg.d_model],
        param_attr=ParamAttr(name=name,
                             initializer=pt.initializer.Normal(
                                 0.0, cfg.d_model ** -0.5)))
    emb = layers.scale(emb, scale=cfg.d_model ** 0.5)
    helper_out = _pos_enc(emb, cfg, pos_offset)
    if cfg.dropout:
        helper_out = layers.dropout(helper_out, cfg.dropout,
                                    is_test=is_test,
                                    dropout_implementation=
                                    "upscale_in_train")
    return helper_out


def _pos_enc(x, cfg, pos_offset=0):
    from ..layer_helper import LayerHelper
    h = LayerHelper("pos_enc")
    out = h.create_variable_for_type_inference(x.dtype, x.shape)
    h.append_op("add_position_encoding", inputs={"X": [x.name]},
                outputs={"Out": [out.name]},
                attrs={"alpha": 1.0, "beta": 1.0,
                       "pos_offset": int(pos_offset)})
    return out


def _ffn(x, cfg, name, is_test):
    h = layers.fc(x, cfg.d_inner, num_flatten_dims=2, act="relu",
                  param_attr=ParamAttr(name=name + "_fc0.w",
                                       initializer=XavierInitializer(),
                                       sharding=(None, "mp")
                                       if cfg.tp else None),
                  bias_attr=ParamAttr(name=name + "_fc0.b"))
    if cfg.dropout:
        h = layers.dropout(h, cfg.dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, cfg.d_model, num_flatten_dims=2,
                     param_attr=ParamAttr(name=name + "_fc1.w",
                                          initializer=XavierInitializer(),
                                          sharding=("mp", None)
                                          if cfg.tp else None),
                     bias_attr=ParamAttr(name=name + "_fc1.b"))


def _prepost(x, residual, cfg, name, is_test):
    """post-process: residual add + layer norm + dropout (reference 'dan')."""
    if residual is not None:
        x = layers.elementwise_add(x, residual)
    return layers.layer_norm(x, begin_norm_axis=2,
                             param_attr=ParamAttr(name=name + "_ln_s"),
                             bias_attr=ParamAttr(name=name + "_ln_b"))


def encoder(src_emb, src_bias, cfg, is_test):
    x = src_emb
    for i in range(cfg.n_layer):
        name = "enc_%d" % i
        attn = multi_head_attention(
            x, None, None, src_bias, cfg.d_model // cfg.n_head,
            cfg.d_model // cfg.n_head, cfg.d_model, cfg.n_head,
            cfg.dropout, name=name + "_att", is_test=is_test)
        x = _prepost(attn, x, cfg, name + "_post_att", is_test)
        ff = _ffn(x, cfg, name + "_ffn", is_test)
        x = _prepost(ff, x, cfg, name + "_post_ffn", is_test)
    return x


def decoder(trg_emb, enc_out, trg_bias, src_bias, cfg, is_test):
    x = trg_emb
    for i in range(cfg.n_layer):
        name = "dec_%d" % i
        self_attn = multi_head_attention(
            x, None, None, trg_bias, cfg.d_model // cfg.n_head,
            cfg.d_model // cfg.n_head, cfg.d_model, cfg.n_head,
            cfg.dropout, name=name + "_self_att", is_test=is_test,
            causal=True)
        x = _prepost(self_attn, x, cfg, name + "_post_self", is_test)
        cross = multi_head_attention(
            x, enc_out, enc_out, src_bias, cfg.d_model // cfg.n_head,
            cfg.d_model // cfg.n_head, cfg.d_model, cfg.n_head,
            cfg.dropout, name=name + "_cross_att", is_test=is_test)
        x = _prepost(cross, x, cfg, name + "_post_cross", is_test)
        ff = _ffn(x, cfg, name + "_ffn", is_test)
        x = _prepost(ff, x, cfg, name + "_post_ffn", is_test)
    return x


def _embed_step(ids_t, cfg, name, pos):
    """Embed a single decode-step token at absolute position ``pos``."""
    return _embed(ids_t, cfg.trg_vocab, cfg, name, True, pos_offset=pos)


def init_decoder_caches(cfg, enc_out, name_prefix="dec"):
    """Per-layer KV caches for incremental decode (reference: the models-repo
    fast_decoder's caches list). Self-attention caches start empty and grow
    by one position per step; cross-attention K/V are projected from the
    encoder output once and reused every step."""
    caches = []
    for i in range(cfg.n_layer):
        name = "%s_%d" % (name_prefix, i)
        sk, sv = mha_kv_projection(
            enc_out, enc_out, cfg.d_model // cfg.n_head,
            cfg.d_model // cfg.n_head, cfg.n_head,
            name=name + "_cross_att")
        caches.append({"self": {"k": None, "v": None},
                       "cross": {"static_k": sk, "static_v": sv}})
    return caches


def decoder_cached_step(x_t, caches, src_bias, cfg, name_prefix="dec"):
    """One decoder pass over a single new token x_t (N, 1, D), attending over
    the KV caches — O(T) per generated token instead of the O(T^2) prefix
    re-decode. Mutates ``caches`` in place (appends this step's K/V)."""
    x = x_t
    for i in range(cfg.n_layer):
        name = "%s_%d" % (name_prefix, i)
        self_attn = multi_head_attention(
            x, None, None, None, cfg.d_model // cfg.n_head,
            cfg.d_model // cfg.n_head, cfg.d_model, cfg.n_head,
            0.0, cache=caches[i]["self"], name=name + "_self_att",
            is_test=True, causal=True)
        x = _prepost(self_attn, x, cfg, name + "_post_self", True)
        cross = multi_head_attention(
            x, None, None, src_bias, cfg.d_model // cfg.n_head,
            cfg.d_model // cfg.n_head, cfg.d_model, cfg.n_head,
            0.0, cache=caches[i]["cross"], name=name + "_cross_att",
            is_test=True)
        x = _prepost(cross, x, cfg, name + "_post_cross", True)
        ff = _ffn(x, cfg, name + "_ffn", True)
        x = _prepost(ff, x, cfg, name + "_post_ffn", True)
    return x


def _attn_bias(mask):
    """(N,T,1) 1/0 mask -> (N,1,1,T) additive bias."""
    m = layers.transpose(mask, [0, 2, 1])
    m = layers.unsqueeze(m, [1])
    return layers.scale(m, scale=10000.0, bias=-10000.0)


def transformer_train_program(cfg, src_len, trg_len, optimizer_fn=None,
                              is_test=False):
    """Feeds: src_ids (N,S,1), src_mask (N,S,1), trg_ids (N,T,1),
    trg_mask (N,T,1), labels (N,T,1)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        src_ids = layers.data("src_ids", [src_len, 1], dtype="int64")
        src_mask = layers.data("src_mask", [src_len, 1], dtype="float32")
        trg_ids = layers.data("trg_ids", [trg_len, 1], dtype="int64")
        trg_mask = layers.data("trg_mask", [trg_len, 1], dtype="float32")
        lbl = layers.data("lbl_ids", [trg_len, 1], dtype="int64")

        src_bias = _attn_bias(src_mask)
        trg_bias = _attn_bias(trg_mask)
        enc_in = _embed(src_ids, cfg.src_vocab, cfg, "src_word_emb", is_test)
        enc_out = encoder(enc_in, src_bias, cfg, is_test)
        dec_in = _embed(trg_ids, cfg.trg_vocab, cfg, "trg_word_emb", is_test)
        dec_out = decoder(dec_in, enc_out, trg_bias, src_bias, cfg, is_test)

        logits = layers.fc(dec_out, cfg.trg_vocab, num_flatten_dims=2,
                           param_attr=ParamAttr(
                               name="dec_out_fc.w",
                               initializer=XavierInitializer()),
                           bias_attr=False)
        if cfg.label_smooth_eps:
            smooth = layers.label_smooth(
                layers.one_hot(lbl, cfg.trg_vocab),
                epsilon=cfg.label_smooth_eps)
            cost = layers.softmax_with_cross_entropy(logits, smooth,
                                                     soft_label=True)
        else:
            cost = layers.softmax_with_cross_entropy(logits, lbl)
        weighted = layers.elementwise_mul(cost, trg_mask)
        sum_cost = layers.reduce_sum(weighted)
        token_num = layers.reduce_sum(trg_mask)
        token_num.stop_gradient = True
        avg_cost = layers.elementwise_div(sum_cost, token_num)
        if optimizer_fn is not None:
            optimizer_fn(avg_cost)
    return main, startup, ["src_ids", "src_mask", "trg_ids", "trg_mask",
                           "lbl_ids"], {"loss": avg_cost}


def greedy_decode_program(cfg, src_len, max_out_len, use_cache=True):
    """Greedy autoregressive decode. With ``use_cache`` (default), each step
    embeds only the newest token and attends over per-layer KV caches —
    O(T) work per token. ``use_cache=False`` keeps the O(T^2) prefix
    re-decode (used as the equivalence oracle in tests)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        src_ids = layers.data("src_ids", [src_len, 1], dtype="int64")
        src_mask = layers.data("src_mask", [src_len, 1], dtype="float32")
        src_bias = _attn_bias(src_mask)
        enc_in = _embed(src_ids, cfg.src_vocab, cfg, "src_word_emb", True)
        enc_out = encoder(enc_in, src_bias, cfg, True)

        if use_cache:
            caches = init_decoder_caches(cfg, enc_out)
            bos = layers.fill_constant_batch_size_like(
                src_ids, [-1, 1, 1], "int64", 0)
            tokens = [bos]
            x_t = _embed_step(bos, cfg, "trg_word_emb", 0)
            for t in range(max_out_len - 1):
                dec_out = decoder_cached_step(x_t, caches, src_bias, cfg)
                logits = layers.fc(dec_out, cfg.trg_vocab,
                                   num_flatten_dims=2,
                                   param_attr=ParamAttr(name="dec_out_fc.w"),
                                   bias_attr=False)       # (N,1,V)
                nxt = layers.unsqueeze(layers.argmax(logits, axis=-1), [2])
                tokens.append(nxt)
                if t + 1 < max_out_len - 1:
                    x_t = _embed_step(nxt, cfg, "trg_word_emb", t + 1)
            trg = layers.concat(tokens, axis=1)           # (N,T,1)
            return main, startup, ["src_ids", "src_mask"], {"out_ids": trg}

        batch = src_ids.shape[0]
        trg = layers.fill_constant_batch_size_like(src_ids,
                                                   [-1, max_out_len, 1],
                                                   "int64", 0)
        ones = layers.fill_constant_batch_size_like(src_ids,
                                                    [-1, max_out_len, 1],
                                                    "float32", 1.0)
        trg_bias = _attn_bias(ones)
        for t in range(max_out_len - 1):
            dec_in = _embed(trg, cfg.trg_vocab, cfg, "trg_word_emb", True)
            dec_out = decoder(dec_in, enc_out, trg_bias, src_bias, cfg, True)
            logits = layers.fc(dec_out, cfg.trg_vocab, num_flatten_dims=2,
                               param_attr=ParamAttr(name="dec_out_fc.w"),
                               bias_attr=False)
            step_logits = layers.slice(logits, axes=[1], starts=[t],
                                       ends=[t + 1])
            nxt = layers.argmax(step_logits, axis=-1)
            nxt = layers.unsqueeze(nxt, [2])
            # write position t+1
            before = layers.slice(trg, axes=[1], starts=[0], ends=[t + 1])
            after = layers.slice(trg, axes=[1], starts=[t + 2],
                                 ends=[max_out_len])
            trg = layers.concat([before, nxt, after], axis=1)
    return main, startup, ["src_ids", "src_mask"], {"out_ids": trg}


def synthetic_batch(cfg, batch, src_len, trg_len, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    return {
        "src_ids": rng.randint(1, cfg.src_vocab,
                               (batch, src_len, 1)).astype(np.int64),
        "src_mask": np.ones((batch, src_len, 1), np.float32),
        "trg_ids": rng.randint(1, cfg.trg_vocab,
                               (batch, trg_len, 1)).astype(np.int64),
        "trg_mask": np.ones((batch, trg_len, 1), np.float32),
        "lbl_ids": rng.randint(1, cfg.trg_vocab,
                               (batch, trg_len, 1)).astype(np.int64),
    }


def beam_search_decode_program(cfg, src_len, max_out_len, beam_size=4,
                               bos_id=0, eos_id=1, len_penalty=0.6,
                               use_cache=True):
    """Beam-search decode (reference: operators/beam_search_op.cc + the
    models-repo fast_decoder). TPU design: beams are a flattened (N*B)
    batch with STATIC shapes; the top-(B*V) frontier is expanded with
    topk + gather — no dynamic LoD beam structures. With ``use_cache``
    (default) each step decodes only the newest token against per-layer KV
    caches, gather-reordering the caches on beam selection; otherwise the
    prefix is re-decoded each step (equivalence oracle).
    Returns out_ids (N, beam, T, 1), scores (N, beam)."""
    import numpy as np
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        src_ids = layers.data("src_ids", [src_len, 1], dtype="int64")
        src_mask = layers.data("src_mask", [src_len, 1], dtype="float32")
        src_bias = _attn_bias(src_mask)
        enc_in = _embed(src_ids, cfg.src_vocab, cfg, "src_word_emb", True)
        enc_out = encoder(enc_in, src_bias, cfg, True)

        b, v, t_max = beam_size, cfg.trg_vocab, max_out_len

        # tile encoder state across beams: (N,S,D) -> (N*B,S,D)
        enc_rep = layers.unsqueeze(enc_out, [1])
        enc_rep = layers.expand(enc_rep, [1, b, 1, 1])
        enc_rep = layers.reshape(enc_rep, [-1, src_len, cfg.d_model])
        bias_rep = layers.unsqueeze(src_bias, [1])
        bias_rep = layers.expand(bias_rep, [1, b, 1, 1, 1])
        bias_rep = layers.reshape(bias_rep, [-1, 1, 1, src_len])

        # scores (N,B): beam0=0, others -1e9 so the
        # first expansion draws B distinct words from beam 0
        zeros_nb = layers.fill_constant_batch_size_like(
            src_ids, [-1, b], "float32", 0.0)
        init_row = layers.assign(
            np.array([[0.0] + [-1e9] * (b - 1)], dtype=np.float32))
        scores = layers.elementwise_add(zeros_nb, init_row)
        # per-(N,B) row index, built from a cumsum of ones (static-safe)
        ones_nb = layers.fill_constant_batch_size_like(
            src_ids, [-1, b], "float32", 1.0)
        row_idx = layers.cast(
            layers.scale(layers.cumsum(ones_nb, axis=0), bias=-1.0),
            "int64")                                        # (N,B)

        if use_cache:
            # project cross-attention K/V from the untiled encoder output
            # (N rows), then tile the head-split result across beams — the
            # projection FCs run once per source row, not once per beam
            caches = init_decoder_caches(cfg, enc_out)
            dh = cfg.d_model // cfg.n_head
            for c in caches:
                for key in ("static_k", "static_v"):
                    x = layers.unsqueeze(c["cross"][key], [1])
                    x = layers.expand(x, [1, b, 1, 1, 1])
                    c["cross"][key] = layers.reshape(
                        x, [-1, cfg.n_head, src_len, dh])
            bos = layers.fill_constant_batch_size_like(
                enc_rep, [-1, 1, 1], "int64", float(bos_id))
            ids_mat = layers.reshape(bos, [-1, 1])        # (N*B, t+1)
            x_t = _embed_step(bos, cfg, "trg_word_emb", 0)
            for t in range(t_max - 1):
                dec_out = decoder_cached_step(x_t, caches, bias_rep, cfg)
                logits = layers.fc(dec_out, v, num_flatten_dims=2,
                                   param_attr=ParamAttr(name="dec_out_fc.w"),
                                   bias_attr=False)        # (N*B,1,V)
                logp = layers.log_softmax(
                    layers.reshape(logits, [-1, v]))       # (N*B,V)
                logp_nbv = layers.reshape(logp, [-1, b * v])
                prev = layers.reshape(scores, [-1, b, 1])
                prev = layers.expand(prev, [1, 1, v])
                prev = layers.reshape(prev, [-1, b * v])
                total = layers.elementwise_add(logp_nbv, prev)
                top_scores, top_idx = layers.topk(total, k=b)   # (N,B)
                beam_sel = layers.cast(
                    layers.elementwise_floordiv(
                        top_idx, layers.fill_constant([1], "int64", v)),
                    "int64")
                word_sel = layers.cast(layers.elementwise_sub(
                    top_idx, layers.scale(beam_sel, scale=float(v))),
                    "int64")
                flat_rows = layers.reshape(
                    layers.elementwise_add(
                        layers.scale(row_idx, scale=float(b)), beam_sel),
                    [-1])                                   # (N*B,)
                # reorder survivors: token history and every layer's
                # self-attention KV cache follow their source beam
                word_col = layers.reshape(word_sel, [-1, 1])
                ids_mat = layers.concat(
                    [layers.gather(ids_mat, flat_rows), word_col], axis=1)
                for c in caches:
                    c["self"]["k"] = layers.gather(c["self"]["k"], flat_rows)
                    c["self"]["v"] = layers.gather(c["self"]["v"], flat_rows)
                scores = top_scores
                if t + 1 < t_max - 1:
                    x_t = _embed_step(layers.reshape(word_col, [-1, 1, 1]),
                                      cfg, "trg_word_emb", t + 1)
            out_ids = layers.reshape(ids_mat, [-1, b, t_max, 1])
            final_scores = layers.scale(
                scores, scale=1.0 / (t_max ** len_penalty))
            return main, startup, ["src_ids", "src_mask"], \
                {"out_ids": out_ids, "scores": final_scores}

        # ids (N*B,T,1) init BOS — full-history buffer for the re-decode path
        ids = layers.fill_constant_batch_size_like(
            enc_rep, [-1, t_max, 1], "int64", float(bos_id))
        ones_mask = layers.fill_constant_batch_size_like(
            enc_rep, [-1, t_max, 1], "float32", 1.0)
        trg_bias = _attn_bias(ones_mask)

        for t in range(t_max - 1):
            dec_in = _embed(ids, cfg.trg_vocab, cfg, "trg_word_emb", True)
            dec_out = decoder(dec_in, enc_rep, trg_bias, bias_rep, cfg,
                              True)
            logits = layers.fc(dec_out, v, num_flatten_dims=2,
                               param_attr=ParamAttr(name="dec_out_fc.w"),
                               bias_attr=False)
            step_logits = layers.slice(logits, axes=[1], starts=[t],
                                       ends=[t + 1])         # (N*B,1,V)
            logp = layers.log_softmax(
                layers.reshape(step_logits, [-1, v]))        # (N*B,V)
            logp_nbv = layers.reshape(logp, [-1, b * v])     # (N,B*V)
            prev = layers.reshape(scores, [-1, b, 1])
            prev = layers.expand(prev, [1, 1, v])
            prev = layers.reshape(prev, [-1, b * v])
            total = layers.elementwise_add(logp_nbv, prev)
            top_scores, top_idx = layers.topk(total, k=b)    # (N,B)
            beam_sel = layers.cast(
                layers.elementwise_floordiv(
                    top_idx, layers.fill_constant([1], "int64", v)),
                "int64")
            word_sel = layers.cast(layers.elementwise_sub(
                top_idx, layers.scale(beam_sel, scale=float(v))), "int64")
            flat_rows = layers.reshape(
                layers.elementwise_add(
                    layers.scale(row_idx, scale=float(b)), beam_sel),
                [-1])                                        # (N*B,)
            ids_kept = layers.gather(
                layers.reshape(ids, [-1, t_max]), flat_rows)  # (N*B,T)
            before = layers.slice(ids_kept, axes=[1], starts=[0],
                                  ends=[t + 1])
            after = layers.slice(ids_kept, axes=[1], starts=[t + 2],
                                 ends=[t_max])
            word_col = layers.reshape(word_sel, [-1, 1])
            ids = layers.reshape(
                layers.concat([before, word_col, after], axis=1),
                [-1, t_max, 1])
            scores = top_scores

        out_ids = layers.reshape(ids, [-1, b, t_max, 1])
        final_scores = layers.scale(scores,
                                    scale=1.0 / (t_max ** len_penalty))
    return main, startup, ["src_ids", "src_mask"], \
        {"out_ids": out_ids, "scores": final_scores}
