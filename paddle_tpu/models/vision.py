"""Classification model zoo beyond ResNet (reference: PaddlePaddle/models
image_classification — mobilenet.py, vgg.py, se_resnext.py).

Static-graph builders in the fluid style; all layers come from
paddle_tpu.layers so these double as integration tests of the conv /
norm / pooling surface.  NCHW, bf16-ready (dtype of the data layer).
"""
import numpy as np

from .. import layers
from ..framework.program import Program, program_guard

__all__ = ["mobilenet_v1", "vgg_net", "se_resnext50",
           "classification_train_program", "synthetic_image_batch"]


def _conv_bn(input, filters, ksize, stride=1, groups=1, act="relu",
             is_test=False):
    conv = layers.conv2d(input, num_filters=filters, filter_size=ksize,
                         stride=stride, padding=(ksize - 1) // 2,
                         groups=groups, bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _depthwise_separable(input, ch_in, ch_out, stride, scale=1.0,
                         is_test=False):
    """MobileNet v1 block: depthwise 3x3 (+BN) then pointwise 1x1 (+BN).
    groups == channels gives XLA a depthwise conv it lowers without an
    im2col blowup."""
    dw = _conv_bn(input, int(ch_in * scale), 3, stride=stride,
                  groups=int(ch_in * scale), is_test=is_test)
    return _conv_bn(dw, int(ch_out * scale), 1, is_test=is_test)


def mobilenet_v1(input, class_dim=1000, scale=1.0, is_test=False):
    """MobileNet-224 v1 (ref models mobilenet.py)."""
    y = _conv_bn(input, int(32 * scale), 3, stride=2, is_test=is_test)
    cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
           (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
          [(512, 1024, 2), (1024, 1024, 1)]
    for ch_in, ch_out, stride in cfg:
        y = _depthwise_separable(y, ch_in, ch_out, stride, scale, is_test)
    pool = layers.pool2d(y, pool_type="avg", global_pooling=True)
    return layers.fc(pool, size=class_dim, act="softmax")


def vgg_net(input, class_dim=1000, layers_cfg=16, is_test=False):
    """VGG-11/13/16/19 (ref models vgg.py)."""
    cfgs = {11: [1, 1, 2, 2, 2], 13: [2, 2, 2, 2, 2],
            16: [2, 2, 3, 3, 3], 19: [2, 2, 4, 4, 4]}
    nums = cfgs[layers_cfg]
    channels = [64, 128, 256, 512, 512]
    y = input
    for reps, ch in zip(nums, channels):
        for _ in range(reps):
            y = layers.conv2d(y, num_filters=ch, filter_size=3, padding=1,
                              act="relu")
        y = layers.pool2d(y, pool_size=2, pool_stride=2, pool_type="max")
    y = layers.fc(y, size=512, act="relu")
    y = layers.dropout(y, dropout_prob=0.5, is_test=is_test)
    y = layers.fc(y, size=512, act="relu")
    y = layers.dropout(y, dropout_prob=0.5, is_test=is_test)
    return layers.fc(y, size=class_dim, act="softmax")


def _squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = layers.pool2d(input, pool_type="avg", global_pooling=True)
    squeeze = layers.fc(pool, size=max(num_channels // reduction_ratio, 4),
                        act="relu")
    excitation = layers.fc(squeeze, size=num_channels, act="sigmoid")
    excitation = layers.reshape(excitation, [-1, num_channels, 1, 1])
    return layers.elementwise_mul(input, excitation)


def _se_bottleneck(input, ch_in, filters, stride, cardinality=32,
                   is_test=False):
    conv0 = _conv_bn(input, filters, 1, is_test=is_test)
    conv1 = _conv_bn(conv0, filters, 3, stride=stride, groups=cardinality,
                     is_test=is_test)
    conv2 = _conv_bn(conv1, filters * 2, 1, act=None, is_test=is_test)
    scaled = _squeeze_excitation(conv2, filters * 2)
    if ch_in != filters * 2 or stride != 1:
        short = _conv_bn(input, filters * 2, 1, stride=stride, act=None,
                         is_test=is_test)
    else:
        short = input
    return layers.relu(layers.elementwise_add(short, scaled))


def se_resnext50(input, class_dim=1000, is_test=False):
    """SE-ResNeXt-50 32x4d (ref models se_resnext.py)."""
    y = _conv_bn(input, 64, 7, stride=2, is_test=is_test)
    y = layers.pool2d(y, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    depth = [3, 4, 6, 3]
    filters = [128, 256, 512, 1024]
    ch_in = 64
    for stage, (reps, f) in enumerate(zip(depth, filters)):
        for i in range(reps):
            y = _se_bottleneck(y, ch_in, f, stride=2 if
                               (i == 0 and stage != 0) else 1,
                               is_test=is_test)
            ch_in = f * 2
    pool = layers.pool2d(y, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool, dropout_prob=0.5, is_test=is_test)
    return layers.fc(drop, size=class_dim, act="softmax")


_ARCHS = {"mobilenet": mobilenet_v1, "vgg16": vgg_net,
          "se_resnext50": se_resnext50}


def classification_train_program(arch, class_dim=1000,
                                 image_shape=(3, 224, 224),
                                 optimizer_fn=None, is_test=False):
    """(main, startup, feeds, fetches) for any zoo classifier."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("image", list(image_shape), "float32")
        label = layers.data("label", [1], "int64")
        prob = _ARCHS[arch](img, class_dim=class_dim, is_test=is_test)
        loss = layers.reduce_mean(layers.cross_entropy(prob, label))
        acc = layers.accuracy(prob, label)
        if optimizer_fn is not None:
            optimizer_fn(loss)
    return main, startup, {"image": img, "label": label}, \
        {"loss": loss, "acc": acc}


def synthetic_image_batch(batch, image_shape=(3, 224, 224), class_dim=1000,
                          seed=0):
    rng = np.random.RandomState(seed)
    return {"image": rng.rand(batch, *image_shape).astype(np.float32),
            "label": rng.randint(0, class_dim, (batch, 1)).astype(np.int64)}
