"""ResNet image classification (static graph).

Reference parity: PaddlePaddle/models image_classification/resnet.py
(BASELINE config "ResNet-50"). NCHW layout; bottleneck blocks; batch norm
with moving stats; standard fc head. bfloat16 option keeps conv/matmul on
the MXU with fp32 BN statistics.
"""
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, name=None, is_test=False):
    conv = layers.conv2d(input, num_filters, filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         param_attr=ParamAttr(name=name + "_weights"),
                         bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test,
                             param_attr=ParamAttr(name=name + "_bn_scale"),
                             bias_attr=ParamAttr(name=name + "_bn_offset"),
                             moving_mean_name=name + "_bn_mean",
                             moving_variance_name=name + "_bn_variance")


def shortcut(input, ch_out, stride, name, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, name=name,
                             is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, name, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          name=name + "_branch2a", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride, act="relu",
                          name=name + "_branch2b", is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1,
                          name=name + "_branch2c", is_test=is_test)
    short = shortcut(input, num_filters * 4, stride, name + "_branch1",
                     is_test=is_test)
    return layers.elementwise_add(short, conv2, act="relu")


def basic_block(input, num_filters, stride, name, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 3, stride=stride, act="relu",
                          name=name + "_branch2a", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3,
                          name=name + "_branch2b", is_test=is_test)
    short = shortcut(input, num_filters, stride, name + "_branch1",
                     is_test=is_test)
    return layers.elementwise_add(short, conv1, act="relu")


_DEPTH_CFG = {
    18: (basic_block, [2, 2, 2, 2]),
    34: (basic_block, [3, 4, 6, 3]),
    50: (bottleneck_block, [3, 4, 6, 3]),
    101: (bottleneck_block, [3, 4, 23, 3]),
    152: (bottleneck_block, [3, 8, 36, 3]),
}


def resnet(input, class_dim=1000, depth=50, is_test=False):
    block_fn, counts = _DEPTH_CFG[depth]
    x = conv_bn_layer(input, 64, 7, stride=2, act="relu", name="conv1",
                      is_test=is_test)
    x = layers.pool2d(x, 3, "max", 2, 1)
    num_filters = [64, 128, 256, 512]
    for b, (nf, cnt) in enumerate(zip(num_filters, counts)):
        for i in range(cnt):
            stride = 2 if i == 0 and b != 0 else 1
            x = block_fn(x, nf, stride, "res%d%c" % (b + 2, ord("a") + i),
                         is_test=is_test)
    pool = layers.pool2d(x, global_pooling=True, pool_type="avg")
    pool = layers.reshape(pool, [0, pool.shape[1]])
    import math
    stdv = 1.0 / math.sqrt(pool.shape[1])
    out = layers.fc(pool, class_dim,
                    param_attr=ParamAttr(
                        name="fc_0.w_0",
                        initializer=pt.initializer.Uniform(-stdv, stdv)),
                    bias_attr=ParamAttr(name="fc_0.b_0"))
    return out


def resnet_train_program(depth=50, class_dim=1000, image_shape=(3, 224, 224),
                         optimizer_fn=None, is_test=False):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        image = layers.data("image", list(image_shape), dtype="float32")
        label = layers.data("label", [1], dtype="int64")
        logits = resnet(image, class_dim, depth, is_test=is_test)
        loss, softmax = layers.softmax_with_cross_entropy(
            logits, label, return_softmax=True)
        loss = layers.mean(loss)
        acc1 = layers.accuracy(softmax, label, k=1)
        acc5 = layers.accuracy(softmax, label,
                               k=min(5, class_dim))
        if optimizer_fn is not None:
            optimizer_fn(loss)
    return main, startup, ["image", "label"], {"loss": loss, "acc1": acc1,
                                               "acc5": acc5}
