"""GPT-style causal language model (static graph) — the long-context
flagship of the zoo.

Reference analogue: the LARK/ERNIE-gen era decoder-only LM configs built
on fluid (same transformer blocks as models/bert.py but causal).
TPU-first choices:
  - pre-LN blocks (stable for deep/long-context training);
  - causal attention through layers.fused_attention: the Pallas flash
    kernel on-chip (the (T,T) score matrix never touches HBM — seq 4k+
    on one chip), impl="ring"/"ulysses" shards the sequence over the
    mesh's `sp` axis for longer-than-chip contexts;
  - bf16 activations with fp32 logits (matmul out_dtype), tied
    embedding decode;
  - recompute option per block (jax.checkpoint) for depth x length.
"""
import math

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.layers.attention import fused_attention
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.initializer import TruncatedNormalInitializer


class GPTConfig(object):
    def __init__(self, vocab_size=32000, hidden_size=768, num_layers=12,
                 num_heads=12, ff_size=3072, max_position=2048,
                 dropout=0.1, initializer_range=0.02, dtype="float32",
                 attn_impl="auto", recompute=False, tp=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ff_size = ff_size
        self.max_position = max_position
        self.dropout = dropout
        self.initializer_range = initializer_range
        self.dtype = dtype
        self.attn_impl = attn_impl      # "auto" | "flash" | "ring" | ...
        self.recompute = recompute
        self.tp = tp


def gpt_base(**kw):
    return GPTConfig(**kw)


def _init(cfg):
    return TruncatedNormalInitializer(scale=cfg.initializer_range)


def _attr(cfg, name, sharding=None):
    return ParamAttr(name=name, initializer=_init(cfg),
                     sharding=sharding if cfg.tp else None)


def _split_heads(x, n_head, d_head):
    # (N, T, H*Dh) -> (N, H, T, Dh)
    x = layers.reshape(x, [0, 0, n_head, d_head])
    return layers.transpose(x, [0, 2, 1, 3])


def _merge_heads(x, d_model):
    x = layers.transpose(x, [0, 2, 1, 3])
    return layers.reshape(x, [0, 0, d_model])


def decoder_block(x, cfg, name, is_test=False):
    """Pre-LN causal transformer block."""
    d = cfg.hidden_size
    dh = d // cfg.num_heads

    ln1 = layers.layer_norm(x, begin_norm_axis=2,
                            param_attr=ParamAttr(name=name + "_ln1_s"),
                            bias_attr=ParamAttr(name=name + "_ln1_b"))
    qkv = layers.fc(ln1, 3 * d, num_flatten_dims=2,
                    param_attr=_attr(cfg, name + "_qkv.w_0", (None, "mp")),
                    bias_attr=ParamAttr(name=name + "_qkv.b_0"))
    q, k, v = layers.split(qkv, 3, dim=2)
    ctx = fused_attention(
        _split_heads(q, cfg.num_heads, dh),
        _split_heads(k, cfg.num_heads, dh),
        _split_heads(v, cfg.num_heads, dh),
        scale=1.0 / math.sqrt(dh), causal=True, impl=cfg.attn_impl)
    attn = layers.fc(_merge_heads(ctx, d), d, num_flatten_dims=2,
                     param_attr=_attr(cfg, name + "_proj.w_0",
                                      ("mp", None)),
                     bias_attr=ParamAttr(name=name + "_proj.b_0"))
    if cfg.dropout:
        attn = layers.dropout(attn, cfg.dropout, is_test=is_test,
                              dropout_implementation="upscale_in_train")
    x = layers.elementwise_add(x, attn)

    ln2 = layers.layer_norm(x, begin_norm_axis=2,
                            param_attr=ParamAttr(name=name + "_ln2_s"),
                            bias_attr=ParamAttr(name=name + "_ln2_b"))
    ff = layers.fc(ln2, cfg.ff_size, num_flatten_dims=2, act="gelu",
                   param_attr=_attr(cfg, name + "_ffn0.w_0",
                                    (None, "mp")),
                   bias_attr=ParamAttr(name=name + "_ffn0.b_0"))
    ff = layers.fc(ff, d, num_flatten_dims=2,
                   param_attr=_attr(cfg, name + "_ffn1.w_0",
                                    ("mp", None)),
                   bias_attr=ParamAttr(name=name + "_ffn1.b_0"))
    if cfg.dropout:
        ff = layers.dropout(ff, cfg.dropout, is_test=is_test,
                            dropout_implementation="upscale_in_train")
    return layers.elementwise_add(x, ff)


def gpt_decoder(token_ids, pos_ids, cfg, is_test=False):
    """Token+position embed -> N pre-LN blocks -> final LN.
    Returns (N, T, H) hidden states (cfg.dtype)."""
    emb = layers.embedding(
        token_ids, [cfg.vocab_size, cfg.hidden_size],
        param_attr=_attr(cfg, "gpt_word_embedding", ("mp", None)),
        dtype="float32")
    pos = layers.embedding(
        pos_ids, [cfg.max_position, cfg.hidden_size],
        param_attr=ParamAttr(name="gpt_pos_embedding",
                             initializer=_init(cfg)),
        dtype="float32")
    x = layers.elementwise_add(emb, pos)
    if cfg.dropout:
        x = layers.dropout(x, cfg.dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    if cfg.dtype == "bfloat16":
        x = layers.cast(x, "bfloat16")
    for i in range(cfg.num_layers):
        if cfg.recompute and not is_test:
            x = layers.recompute_segment(
                lambda h, i=i: decoder_block(h, cfg, "gpt_layer_%d" % i,
                                             is_test=is_test), [x])
        else:
            x = decoder_block(x, cfg, "gpt_layer_%d" % i, is_test=is_test)
    return layers.layer_norm(x, begin_norm_axis=2,
                             param_attr=ParamAttr(name="gpt_lnf_s"),
                             bias_attr=ParamAttr(name="gpt_lnf_b"))


def gpt_pretrain_program(cfg, batch_size, seq_len, optimizer_fn=None,
                         is_test=False):
    """Next-token LM: feeds token_ids/pos_ids/labels (N,T,1) int64 +
    loss_mask (N,T,1) float32 (1 = predict here). Tied-embedding decode
    in bf16 with f32 accumulation when cfg.dtype is bfloat16."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        tok = layers.data("token_ids", [seq_len, 1], dtype="int64")
        pos = layers.data("pos_ids", [seq_len, 1], dtype="int64")
        lbl = layers.data("labels", [seq_len, 1], dtype="int64")
        lmask = layers.data("loss_mask", [seq_len, 1], dtype="float32")

        h = gpt_decoder(tok, pos, cfg, is_test=is_test)  # cfg.dtype
        # fused tied-embedding head: the (N*T, vocab) logits exist only
        # inside the op (Pallas keeps them out of HBM under use_pallas;
        # the XLA fallback is the same _tied_logits+CE math). Decode
        # programs (gpt_logits_program) still materialize logits — they
        # ARE the output there.
        flat_h = layers.reshape(h, [-1, cfg.hidden_size])
        flat_lbl = layers.reshape(lbl, [-1, 1])
        emb = main.global_block().var("gpt_word_embedding")
        ce = layers.fused_mlm_head_loss(
            flat_h, emb, flat_lbl, cast_bf16=cfg.dtype == "bfloat16")
        mask = layers.reshape(lmask, [-1, 1])
        loss = layers.elementwise_div(
            layers.reduce_sum(layers.elementwise_mul(ce, mask)),
            layers.elementwise_add(
                layers.reduce_sum(mask),
                layers.fill_constant([1], "float32", 1e-8)))
        if optimizer_fn is not None:
            optimizer_fn(loss)
    feeds = ["token_ids", "pos_ids", "labels", "loss_mask"]
    return main, startup, feeds, {"loss": loss}


def _tied_logits(cfg, h, main):
    """Tied-embedding vocab projection, shared by the train and decode
    programs (their parity is what makes a trained scope decodable)."""
    emb = main.global_block().var("gpt_word_embedding")
    if cfg.dtype == "bfloat16":
        return layers.matmul(h, layers.cast(emb, "bfloat16"),
                             transpose_y=True, out_dtype="float32")
    return layers.matmul(h, emb, transpose_y=True)


def gpt_logits_program(cfg, seq_len):
    """Inference program: token_ids/pos_ids -> (N,T,vocab) f32 logits
    (shared parameter names with gpt_pretrain_program, so a trained
    scope serves decode directly)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        tok = layers.data("token_ids", [seq_len, 1], dtype="int64")
        pos = layers.data("pos_ids", [seq_len, 1], dtype="int64")
        h = gpt_decoder(tok, pos, cfg, is_test=True)
        logits = _tied_logits(cfg, h, main)
    return main, startup, ["token_ids", "pos_ids"], {"logits": logits}


def greedy_generate(exe, cfg, prompt_tokens, max_new_tokens,
                    logits_program=None, temperature=0.0, seed=0):
    """Autoregressive decode: full-prefix forward per new token at ONE
    static length (prompt+max_new, so a single compiled program serves
    every step — the static-shape idiom; causal masking makes the
    padding positions irrelevant). temperature=0 -> greedy argmax.
    prompt_tokens: (N, P) int. Returns (N, P+max_new) int tokens."""
    import numpy as np
    prompt = np.asarray(prompt_tokens, np.int64)
    n, p = prompt.shape
    total = p + max_new_tokens
    if total > cfg.max_position:
        # the position table would silently clamp past its last row
        raise ValueError(
            "prompt (%d) + max_new_tokens (%d) exceeds cfg.max_position "
            "(%d)" % (p, max_new_tokens, cfg.max_position))
    if logits_program is None:
        logits_program = gpt_logits_program(cfg, total)
    main, startup, feeds, fetch = logits_program
    toks = np.zeros((n, total), np.int64)
    toks[:, :p] = prompt
    pos = np.tile(np.arange(total).reshape(1, total, 1),
                  (n, 1, 1)).astype(np.int64)
    rng = np.random.RandomState(seed)
    for cur in range(p, total):
        out, = exe.run(main, feed={"token_ids": toks[:, :, None],
                                   "pos_ids": pos},
                       fetch_list=[fetch["logits"]],
                       return_numpy=True)
        step_logits = np.asarray(out)[:, cur - 1, :]
        if temperature and temperature > 0:
            z = step_logits / temperature
            z = z - z.max(axis=-1, keepdims=True)
            probs = np.exp(z) / np.exp(z).sum(axis=-1, keepdims=True)
            nxt = np.array([rng.choice(cfg.vocab_size, p=probs[i])
                            for i in range(n)])
        else:
            nxt = step_logits.argmax(axis=-1)
        toks[:, cur] = nxt
    return toks


def synthetic_batch(cfg, batch_size, seq_len, seed=0):
    """Random-but-valid LM batch: labels are tokens shifted left."""
    import numpy as np
    rng = np.random.RandomState(seed)
    toks = rng.randint(0, cfg.vocab_size,
                       (batch_size, seq_len + 1)).astype(np.int64)
    pos = np.tile(np.arange(seq_len).reshape(1, seq_len, 1),
                  (batch_size, 1, 1)).astype(np.int64)
    return {"token_ids": toks[:, :-1, None],
            "pos_ids": pos,
            "labels": toks[:, 1:, None],
            "loss_mask": np.ones((batch_size, seq_len, 1), np.float32)}
