"""DeepFM CTR model (high-dim sparse embeddings).

Reference parity: PaddlePaddle/models ctr/deepfm (BASELINE config). The
reference trains this on the pserver path (distributed lookup tables,
transpiler); TPU-native: ONE big embedding table sharded over the mesh
("mp" rows) — XLA turns lookups into all-to-all gathers over ICI, gradients
into scatter-adds; no parameter servers.

Criteo-style input: 13 dense features + 26 categorical field ids hashed
into a shared feature space.
"""
import math

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr


def deepfm(raw_dense, sparse_ids, feature_dim, embedding_size=10,
           layer_sizes=(400, 400, 400), sparse_fields=26,
           shard_embeddings=False, is_test=False):
    """raw_dense: (N, 13) float; sparse_ids: (N, 26, 1) int64.
    Returns (predict (N,1) prob, aux dict)."""
    init = pt.initializer.TruncatedNormalInitializer(
        scale=1.0 / math.sqrt(feature_dim))
    emb_attr = ParamAttr(name="feat_embeddings", initializer=init,
                         sharding=("mp", None) if shard_embeddings else None)
    w1_attr = ParamAttr(name="feat_weights_1st", initializer=init,
                        sharding=("mp",) if shard_embeddings else None)

    # ---- first order ----
    w1 = layers.embedding(sparse_ids, [feature_dim, 1], param_attr=w1_attr)
    first_sparse = layers.reduce_sum(layers.reshape(
        w1, [0, sparse_fields]), dim=1, keep_dim=True)
    dense_w = layers.fc(raw_dense, 1, bias_attr=False,
                        param_attr=ParamAttr(name="dense_w1"))
    y_first = layers.elementwise_add(first_sparse, dense_w)

    # ---- second order: FM sum-square trick ----
    emb = layers.embedding(sparse_ids, [feature_dim, embedding_size],
                           param_attr=emb_attr)          # (N, 26, E)
    summed = layers.reduce_sum(emb, dim=1)               # (N, E)
    summed_sq = layers.square(summed)
    sq = layers.square(emb)
    sq_summed = layers.reduce_sum(sq, dim=1)
    y_second = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(summed_sq, sq_summed),
                          dim=1, keep_dim=True), scale=0.5)

    # ---- deep tower ----
    deep = layers.reshape(emb, [0, sparse_fields * embedding_size])
    deep = layers.concat([deep, raw_dense], axis=1)
    for i, sz in enumerate(layer_sizes):
        deep = layers.fc(deep, sz, act="relu",
                         param_attr=ParamAttr(
                             name="deep_fc_%d.w" % i,
                             initializer=pt.initializer.Normal(
                                 0.0, math.sqrt(2.0 / sz))),
                         bias_attr=ParamAttr(name="deep_fc_%d.b" % i))
    y_deep = layers.fc(deep, 1, param_attr=ParamAttr(name="deep_out.w"),
                       bias_attr=ParamAttr(name="deep_out.b"))

    logit = layers.elementwise_add(
        layers.elementwise_add(y_first, y_second), y_deep)
    predict = layers.sigmoid(logit)
    return logit, predict


def deepfm_train_program(feature_dim=1000000, embedding_size=10,
                         sparse_fields=26, dense_dim=13,
                         optimizer_fn=None, shard_embeddings=False,
                         is_test=False):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        dense = layers.data("dense_input", [dense_dim], dtype="float32")
        sparse = layers.data("sparse_input", [sparse_fields, 1],
                             dtype="int64")
        label = layers.data("label", [1], dtype="float32")
        logit, predict = deepfm(dense, sparse, feature_dim, embedding_size,
                                sparse_fields=sparse_fields,
                                shard_embeddings=shard_embeddings,
                                is_test=is_test)
        loss = layers.mean(
            layers.sigmoid_cross_entropy_with_logits(logit, label))
        two_col = layers.concat(
            [layers.elementwise_sub(layers.ones_like(predict), predict),
             predict], axis=1)
        auc_out, _ = layers.auc(two_col, layers.cast(label, "int64"))
        if optimizer_fn is not None:
            optimizer_fn(loss)
    return main, startup, ["dense_input", "sparse_input", "label"], \
        {"loss": loss, "auc": auc_out, "predict": predict}


def synthetic_batch(batch_size, feature_dim=1000000, sparse_fields=26,
                    dense_dim=13, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    return {
        "dense_input": rng.rand(batch_size, dense_dim).astype(np.float32),
        "sparse_input": rng.randint(
            0, feature_dim, (batch_size, sparse_fields, 1)).astype(np.int64),
        "label": (rng.rand(batch_size, 1) > 0.5).astype(np.float32),
    }
