"""Model zoo — the BASELINE.json configs (reference: PaddlePaddle/models +
LARK/ERNIE repos, rebuilt on paddle_tpu layers).

- bert: BERT-base / ERNIE 1.0 pretraining (flagship benchmark)
- resnet: ResNet-50 image classification
- transformer: Transformer-base NMT
- deepfm: DeepFM CTR with high-dim sparse embeddings
- simple: MLP/word2vec smoke models (book tests)
- vision: MobileNet v1 / VGG-16 / SE-ResNeXt-50 classifiers
- yolov3: YOLOv3 detection (train: yolov3_loss; infer: yolo_box+NMS)
- sequence_labeling: BiGRU-CRF tagger (LAC/NER style)
- ocr: CRNN-CTC text recognition
- gpt: GPT-style causal LM (long-context flagship: flash/ring/ulysses
  attention, greedy_generate decode)
- dcgan: DCGAN adversarial training as one fused two-optimizer step
"""
from . import bert
from . import resnet
from . import transformer
from . import deepfm
from . import simple
from . import vision
from . import yolov3
from . import sequence_labeling
from . import ocr
from . import gpt
from . import dcgan
