"""Model zoo — the BASELINE.json configs (reference: PaddlePaddle/models +
LARK/ERNIE repos, rebuilt on paddle_tpu layers).

- bert: BERT-base / ERNIE 1.0 pretraining (flagship benchmark)
- resnet: ResNet-50 image classification
- transformer: Transformer-base NMT
- deepfm: DeepFM CTR with high-dim sparse embeddings
- simple: MLP/word2vec smoke models (book tests)
"""
from . import bert
from . import resnet
from . import transformer
from . import deepfm
from . import simple
