"""YOLOv3 object detection (reference: PaddlePaddle/models
yolov3 — models/yolov3.py + the fluid detection op suite).

A darknet-style backbone with the standard 3-scale YOLOv3 heads, built
entirely from paddle_tpu.layers: training uses ``yolov3_loss`` per
scale; inference uses ``yolo_box`` + ``multiclass_nms``.  The
``tiny=True`` configuration shrinks channels/depth for smoke tests and
single-chip benches while keeping every op on the real code path.
"""
import numpy as np

from .. import layers
from ..framework.program import Program, program_guard

__all__ = ["yolov3_body", "yolov3_train_program", "yolov3_infer_program",
           "synthetic_detection_batch", "YOLO_ANCHORS"]

YOLO_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119, 116, 90,
                156, 198, 373, 326]
YOLO_ANCHOR_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]


def _conv_bn(x, ch, ksize, stride=1, is_test=False):
    y = layers.conv2d(x, num_filters=ch, filter_size=ksize, stride=stride,
                      padding=(ksize - 1) // 2, bias_attr=False)
    return layers.batch_norm(y, act=None, is_test=is_test)


def _dark_block(x, ch, is_test=False):
    y = layers.leaky_relu(_conv_bn(x, ch, 1, is_test=is_test), alpha=0.1)
    y = layers.leaky_relu(_conv_bn(y, ch * 2, 3, is_test=is_test),
                          alpha=0.1)
    return layers.elementwise_add(x, y)


def yolov3_body(image, class_num=80, tiny=True, is_test=False):
    """Backbone + 3 detection heads.  Returns the list of raw head
    tensors (N, mask*(5+classes), H_s, W_s) for downsample 32/16/8."""
    w = 8 if tiny else 32
    depths = [1, 1, 2] if tiny else [1, 2, 8]
    y = layers.leaky_relu(_conv_bn(image, w, 3, is_test=is_test), 0.1)
    routes = []
    for stage, reps in enumerate(depths):
        y = layers.leaky_relu(
            _conv_bn(y, w * 2 ** (stage + 1), 3, stride=2,
                     is_test=is_test), 0.1)
        for _ in range(reps):
            y = _dark_block(y, w * 2 ** stage, is_test=is_test)
        routes.append(y)
    # two more downsamples to reach stride 32
    for extra in range(2):
        y = layers.leaky_relu(
            _conv_bn(y, w * 2 ** (4 + extra), 3, stride=2,
                     is_test=is_test), 0.1)
        routes.append(y)
    heads = []
    # heads at stride 32, 16, 8 with top-down feature reuse
    if image.shape[2] % 32 or image.shape[3] % 32:
        raise ValueError(
            "yolov3_body needs the image size divisible by 32 so the "
            "top-down FPN upsample aligns across strides; got %r" %
            (tuple(image.shape[2:]),))
    route = None
    for i, feat in enumerate(routes[::-1][:3]):
        if route is not None:
            route = layers.resize_nearest(route, scale=2.0)
            if route.shape[2] != feat.shape[2]:
                raise ValueError(
                    "FPN shape mismatch: upsampled route %r vs feature "
                    "%r" % (tuple(route.shape), tuple(feat.shape)))
            feat = layers.concat([route, feat], axis=1)
        ch = feat.shape[1]
        tip = layers.leaky_relu(_conv_bn(feat, ch, 3, is_test=is_test),
                                0.1)
        n_mask = len(YOLO_ANCHOR_MASKS[i])
        head = layers.conv2d(tip, num_filters=n_mask * (5 + class_num),
                             filter_size=1)
        heads.append(head)
        route = tip
    return heads


def yolov3_train_program(class_num=4, image_size=96, max_box=10,
                         tiny=True, optimizer_fn=None):
    """(main, startup, feeds, fetches): summed 3-scale yolov3_loss."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("image", [3, image_size, image_size], "float32")
        gt_box = layers.data("gt_box", [max_box, 4], "float32")
        gt_label = layers.data("gt_label", [max_box], "int32")
        heads = yolov3_body(img, class_num=class_num, tiny=tiny)
        losses = []
        for head, mask, down in zip(heads, YOLO_ANCHOR_MASKS, [32, 16, 8]):
            l = layers.yolov3_loss(
                head, gt_box, gt_label, anchors=YOLO_ANCHORS,
                anchor_mask=mask, class_num=class_num, ignore_thresh=0.7,
                downsample_ratio=down, use_label_smooth=False)
            losses.append(layers.reduce_mean(l))
        loss = losses[0]
        for l in losses[1:]:
            loss = layers.elementwise_add(loss, l)
        if optimizer_fn is not None:
            optimizer_fn(loss)
    return main, startup, \
        {"image": img, "gt_box": gt_box, "gt_label": gt_label}, \
        {"loss": loss}


def yolov3_infer_program(class_num=4, image_size=96, tiny=True,
                         conf_thresh=0.01, nms_topk=100, keep_topk=50,
                         nms_thresh=0.45):
    """(main, startup, feeds, fetches): yolo_box per scale + NMS."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("image", [3, image_size, image_size], "float32")
        im_size = layers.data("im_size", [2], "int32")
        heads = yolov3_body(img, class_num=class_num, tiny=tiny,
                            is_test=True)
        boxes, scores = [], []
        for head, mask, down in zip(heads, YOLO_ANCHOR_MASKS, [32, 16, 8]):
            anchors = []
            for m in mask:
                anchors.extend(YOLO_ANCHORS[2 * m:2 * m + 2])
            b, s = layers.yolo_box(head, im_size, anchors=anchors,
                                   class_num=class_num,
                                   conf_thresh=conf_thresh,
                                   downsample_ratio=down)
            boxes.append(b)
            scores.append(layers.transpose(s, perm=[0, 2, 1]))
        all_boxes = layers.concat(boxes, axis=1)
        all_scores = layers.concat(scores, axis=2)
        pred = layers.multiclass_nms(
            all_boxes, all_scores, score_threshold=conf_thresh,
            nms_top_k=nms_topk, keep_top_k=keep_topk,
            nms_threshold=nms_thresh, background_label=-1)
    return main, startup, {"image": img, "im_size": im_size}, \
        {"pred": pred}


def synthetic_detection_batch(batch, image_size=96, max_box=10,
                              class_num=4, seed=0):
    rng = np.random.RandomState(seed)
    # normalized xywh gt boxes, zero-padded rows past the true count
    boxes = np.zeros((batch, max_box, 4), np.float32)
    labels = np.zeros((batch, max_box), np.int32)
    for b in range(batch):
        n = rng.randint(1, max_box // 2)
        cx, cy = rng.uniform(0.2, 0.8, (2, n))
        w, h = rng.uniform(0.05, 0.3, (2, n))
        boxes[b, :n] = np.stack([cx, cy, w, h], axis=1)
        labels[b, :n] = rng.randint(0, class_num, n)
    return {"image": rng.rand(batch, 3, image_size,
                              image_size).astype(np.float32),
            "gt_box": boxes, "gt_label": labels}
