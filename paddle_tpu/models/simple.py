"""Smoke-test models (reference tests/book/): mnist-style MLP, word2vec.
"""
import paddle_tpu as pt
from paddle_tpu import layers


def mlp_classifier_program(input_dim=784, hidden=(200, 200), classes=10,
                           optimizer_fn=None):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [input_dim], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        h = x
        for sz in hidden:
            h = layers.fc(h, sz, act="relu")
        logits = layers.fc(h, classes)
        loss, softmax = layers.softmax_with_cross_entropy(
            logits, y, return_softmax=True)
        loss = layers.mean(loss)
        acc = layers.accuracy(softmax, y)
        if optimizer_fn is not None:
            optimizer_fn(loss)
    return main, startup, ["x", "y"], {"loss": loss, "acc": acc}


def word2vec_program(vocab_size=1000, emb_size=64, window=2,
                     optimizer_fn=None):
    """CBOW word2vec (reference book/04.word2vec)."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ctx_words = []
        for i in range(2 * window):
            w = layers.data("ctx_%d" % i, [1], dtype="int64")
            ctx_words.append(w)
        target = layers.data("target", [1], dtype="int64")
        embs = [layers.embedding(
            w, [vocab_size, emb_size],
            param_attr=pt.ParamAttr(name="shared_w"))
            for w in ctx_words]
        stacked = layers.stack(embs, axis=1)       # (N, 2w, E)
        avg = layers.reduce_mean(stacked, dim=1)
        logits = layers.fc(avg, vocab_size)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, target))
        if optimizer_fn is not None:
            optimizer_fn(loss)
    feeds = ["ctx_%d" % i for i in range(2 * window)] + ["target"]
    return main, startup, feeds, {"loss": loss}
