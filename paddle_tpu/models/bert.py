"""BERT-base / ERNIE 1.0 pretraining model (static graph).

Reference parity: LARK/ERNIE `model/bert.py` (+ PaddlePaddle/models), the
BASELINE.json flagship config. TPU-first choices:
  - bfloat16 activations with fp32 layernorm statistics and fp32 master
    optimizer math (ops/optimizer_ops.py) — MXU-native precision;
  - fused attention op (XLA/Pallas flash) instead of composed matmuls;
  - masked-LM gather over a STATIC number of mask positions per batch
    (max_preds_per_seq), the padded-dense idiom replacing LoD select;
  - tensor-parallel options: attention/ffn weights annotated for the "mp"
    mesh axis when tp=True, batch sharded over "dp" by CompiledProgram.
"""
import math

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.layers.attention import multi_head_attention
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.initializer import TruncatedNormalInitializer


class BertConfig(object):
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, ff_size=3072, max_position=512,
                 type_vocab_size=2, hidden_dropout=0.1, attn_dropout=0.1,
                 initializer_range=0.02, dtype="float32", tp=False,
                 recompute=False, attn_impl="auto"):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ff_size = ff_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout = hidden_dropout
        self.attn_dropout = attn_dropout
        self.initializer_range = initializer_range
        self.dtype = dtype
        self.tp = tp
        # "ring"/"ulysses" shard the sequence over the mesh's sp axis;
        # the (N,1,1,T) padding bias rides along (key-padding masks are
        # first-class in both sequence-parallel paths)
        self.attn_impl = attn_impl
        # rematerialize each encoder layer (jax.checkpoint): ~T*H HBM per
        # layer traded for one extra forward in backward — how long-context
        # / large-batch configs fit on a chip
        self.recompute = recompute


def bert_base(**kw):
    return BertConfig(**kw)


def bert_large(**kw):
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_layers", 24)
    kw.setdefault("num_heads", 16)
    kw.setdefault("ff_size", 4096)
    return BertConfig(**kw)


def _init(cfg):
    return TruncatedNormalInitializer(scale=cfg.initializer_range)


def _attr(cfg, name, sharding=None):
    return ParamAttr(name=name, initializer=_init(cfg),
                     sharding=sharding if cfg.tp else None)


def encoder_layer(x, attn_bias, cfg, name, is_test=False):
    """Post-LN transformer layer (BERT structure)."""
    d = cfg.hidden_size
    attn = multi_head_attention(
        x, None, None, attn_bias, d // cfg.num_heads, d // cfg.num_heads,
        d, n_head=cfg.num_heads, dropout_rate=cfg.attn_dropout,
        param_initializer=_init(cfg), name=name + "_multi_head_att",
        is_test=is_test, attn_impl=getattr(cfg, "attn_impl", "auto"))
    if cfg.hidden_dropout:
        attn = layers.dropout(attn, cfg.hidden_dropout, is_test=is_test,
                              dropout_implementation="upscale_in_train")
    x = layers.layer_norm(layers.elementwise_add(x, attn),
                          begin_norm_axis=2,
                          param_attr=ParamAttr(name=name + "_post_att_ln_s"),
                          bias_attr=ParamAttr(name=name + "_post_att_ln_b"))
    ff = layers.fc(x, cfg.ff_size, num_flatten_dims=2, act="gelu",
                   param_attr=_attr(cfg, name + "_ffn_fc_0.w_0",
                                    (None, "mp")),
                   bias_attr=ParamAttr(name=name + "_ffn_fc_0.b_0"))
    ff = layers.fc(ff, d, num_flatten_dims=2,
                   param_attr=_attr(cfg, name + "_ffn_fc_1.w_0",
                                    ("mp", None)),
                   bias_attr=ParamAttr(name=name + "_ffn_fc_1.b_0"))
    if cfg.hidden_dropout:
        ff = layers.dropout(ff, cfg.hidden_dropout, is_test=is_test,
                            dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, ff),
                             begin_norm_axis=2,
                             param_attr=ParamAttr(name=name + "_post_ffn_ln_s"),
                             bias_attr=ParamAttr(name=name + "_post_ffn_ln_b"))


def bert_encoder(src_ids, position_ids, sentence_ids, input_mask, cfg,
                 is_test=False, task_ids=None, task_vocab_size=16):
    """Returns (sequence_output (N,T,H), pooled [CLS] output (N,H)).
    task_ids (ERNIE 2.0 continual multi-task) adds a task-type embedding."""
    emb = layers.embedding(
        src_ids, [cfg.vocab_size, cfg.hidden_size],
        param_attr=_attr(cfg, "word_embedding", ("mp", None)),
        dtype="float32")
    pos = layers.embedding(
        position_ids, [cfg.max_position, cfg.hidden_size],
        param_attr=ParamAttr(name="pos_embedding", initializer=_init(cfg)),
        dtype="float32")
    sent = layers.embedding(
        sentence_ids, [cfg.type_vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="sent_embedding", initializer=_init(cfg)),
        dtype="float32")
    x = layers.elementwise_add(layers.elementwise_add(emb, pos), sent)
    if task_ids is not None:
        task = layers.embedding(
            task_ids, [task_vocab_size, cfg.hidden_size],
            param_attr=ParamAttr(name="task_embedding",
                                 initializer=_init(cfg)),
            dtype="float32")
        x = layers.elementwise_add(x, task)
    x = layers.layer_norm(x, begin_norm_axis=2,
                          param_attr=ParamAttr(name="pre_encoder_ln_s"),
                          bias_attr=ParamAttr(name="pre_encoder_ln_b"))
    if cfg.hidden_dropout:
        x = layers.dropout(x, cfg.hidden_dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    if cfg.dtype == "bfloat16":
        x = layers.cast(x, "bfloat16")

    # attn bias: (N,1,1,T); mask 1=token/0=pad -> additive 0 / -1e4,
    # broadcast over heads and query positions
    mask_t = layers.transpose(input_mask, [0, 2, 1])   # (N,1,T)
    mask_t = layers.unsqueeze(mask_t, [1])             # (N,1,1,T)
    attn_bias = layers.scale(mask_t, scale=10000.0, bias=-10000.0)
    if cfg.dtype == "bfloat16":
        attn_bias = layers.cast(attn_bias, "bfloat16")

    for i in range(cfg.num_layers):
        if cfg.recompute and not is_test:
            x = layers.recompute_segment(
                lambda h, i=i: encoder_layer(
                    h, attn_bias, cfg, "encoder_layer_%d" % i,
                    is_test=is_test), [x])
        else:
            x = encoder_layer(x, attn_bias, cfg, "encoder_layer_%d" % i,
                              is_test=is_test)
    if cfg.dtype == "bfloat16":
        x = layers.cast(x, "float32")

    cls = layers.slice(x, axes=[1], starts=[0], ends=[1])
    cls = layers.reshape(cls, [0, cfg.hidden_size])
    pooled = layers.fc(cls, cfg.hidden_size, act="tanh",
                       param_attr=ParamAttr(name="pooled_fc.w_0",
                                            initializer=_init(cfg)),
                       bias_attr=ParamAttr(name="pooled_fc.b_0"))
    return x, pooled


# The tied-embedding vocab projection now lives INSIDE
# layers.fused_mlm_head_loss (cast_bf16= keeps the bf16-matmul-with-f32-
# accumulation MXU trick): the (preds x vocab) logits tensor is an op-
# internal detail, which is what lets the Pallas blockwise kernel keep
# it out of HBM entirely under BuildStrategy.use_pallas.


def bert_pretrain_program(cfg, batch_size, seq_len, max_preds_per_seq=20,
                          is_test=False, optimizer_fn=None):
    """Build main+startup programs for MLM+NSP pretraining.

    Feeds: src_ids, pos_ids, sent_ids (N,T,1) int64; input_mask (N,T,1)
    float; mask_pos (N*max_preds,1) int64 flat indices into (N*T);
    mask_label (N*max_preds,1) int64; labels (N,1) int64 (NSP).
    Returns (main, startup, feeds dict, fetch dict).
    """
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        src_ids = layers.data("src_ids", [seq_len, 1], dtype="int64")
        pos_ids = layers.data("pos_ids", [seq_len, 1], dtype="int64")
        sent_ids = layers.data("sent_ids", [seq_len, 1], dtype="int64")
        input_mask = layers.data("input_mask", [seq_len, 1],
                                 dtype="float32")
        mask_pos = layers.data("mask_pos", [1], dtype="int64")
        mask_label = layers.data("mask_label", [1], dtype="int64")
        nsp_label = layers.data("labels", [1], dtype="int64")

        seq_out, pooled = bert_encoder(src_ids, pos_ids, sent_ids,
                                       input_mask, cfg, is_test=is_test)

        # ---- masked LM head ----
        flat = layers.reshape(seq_out, [-1, cfg.hidden_size])
        picked = layers.gather(flat, mask_pos)
        trans = layers.fc(picked, cfg.hidden_size, act="gelu",
                          param_attr=ParamAttr(name="mask_lm_trans_fc.w_0",
                                               initializer=_init(cfg)),
                          bias_attr=ParamAttr(name="mask_lm_trans_fc.b_0"))
        trans = layers.layer_norm(
            trans, begin_norm_axis=1,
            param_attr=ParamAttr(name="mask_lm_trans_ln_s"),
            bias_attr=ParamAttr(name="mask_lm_trans_ln_b"))
        # decode with tied word embedding (reference: weight sharing),
        # fused with the CE: the (preds, vocab) logits exist only inside
        # fused_mlm_head_loss — under use_pallas the blockwise kernel
        # keeps them out of HBM in fwd AND bwd; the XLA fallback is the
        # same matmul(+bias)+CE math as the old unfused chain
        word_emb = main.global_block().var("word_embedding")
        mlm_bias = layers.create_parameter(
            [cfg.vocab_size], "float32", name="mask_lm_out_fc.b_0",
            default_initializer=pt.initializer.Constant(0.0))
        mlm_loss = layers.mean(layers.fused_mlm_head_loss(
            trans, word_emb, mask_label, bias=mlm_bias,
            cast_bf16=cfg.dtype == "bfloat16"))

        # ---- NSP head ----
        nsp_logits = layers.fc(
            pooled, 2, param_attr=ParamAttr(name="next_sent_fc.w_0",
                                            initializer=_init(cfg)),
            bias_attr=ParamAttr(name="next_sent_fc.b_0"))
        nsp_loss, nsp_softmax = layers.softmax_with_cross_entropy(
            nsp_logits, nsp_label, return_softmax=True)
        nsp_acc = layers.accuracy(nsp_softmax, nsp_label)
        nsp_loss = layers.mean(nsp_loss)

        loss = layers.elementwise_add(mlm_loss, nsp_loss)
        if optimizer_fn is not None:
            optimizer_fn(loss)
    feeds = ["src_ids", "pos_ids", "sent_ids", "input_mask", "mask_pos",
             "mask_label", "labels"]
    fetch = {"loss": loss, "mlm_loss": mlm_loss, "nsp_loss": nsp_loss,
             "nsp_acc": nsp_acc}
    return main, startup, feeds, fetch


def synthetic_batch(cfg, batch_size, seq_len, max_preds_per_seq=20, seed=0):
    """Random-but-valid pretraining batch (reference: data generators)."""
    import numpy as np
    rng = np.random.RandomState(seed)
    n, t = batch_size, seq_len
    src = rng.randint(0, cfg.vocab_size, (n, t, 1)).astype(np.int64)
    pos = np.tile(np.arange(t).reshape(1, t, 1), (n, 1, 1)).astype(np.int64)
    sent = np.zeros((n, t, 1), np.int64)
    sent[:, t // 2:, :] = 1
    mask = np.ones((n, t, 1), np.float32)
    mp = np.stack([rng.choice(t, max_preds_per_seq, replace=False) + i * t
                   for i in range(n)]).reshape(-1, 1).astype(np.int64)
    ml = rng.randint(0, cfg.vocab_size,
                     (n * max_preds_per_seq, 1)).astype(np.int64)
    nsp = rng.randint(0, 2, (n, 1)).astype(np.int64)
    return {"src_ids": src, "pos_ids": pos, "sent_ids": sent,
            "input_mask": mask, "mask_pos": mp, "mask_label": ml,
            "labels": nsp}


# ERNIE 1.0 is architecturally BERT with phrase/entity masking in the DATA
# pipeline (reference ERNIE repo); expose the alias + masking helper.
ErnieConfig = BertConfig
ernie_base = bert_base
ernie_pretrain_program = bert_pretrain_program


# ---------------------------------------------------------------------------
# ERNIE 2.0 continual multi-task pretraining (BASELINE stretch config).
# Reference: ERNIE 2.0 paper / LARK repo — BERT-style encoder + task-id
# embedding + a battery of heads (word-aware / structure-aware /
# semantic-aware) trained jointly; losses summed with per-task weights.
# ---------------------------------------------------------------------------

def ernie2_large(**kw):
    """ERNIE 2.0-large: BERT-large geometry + task-id embedding, the
    BASELINE stretch config (ERNIE 2.0 paper, Table 1 'large'). tp=True
    annotates mp shardings for pod-scale tensor parallelism."""
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_layers", 24)
    kw.setdefault("num_heads", 16)
    kw.setdefault("ff_size", 4096)
    kw.setdefault("tp", True)
    return BertConfig(**kw)


def ernie2_task_schedule(n_steps, weights=(1.0, 1.0, 1.0), seed=0):
    """Per-step task sampling (ERNIE 2.0's sequential multi-task learning:
    each step trains one task sampled proportionally to its weight, so
    earlier tasks keep being revisited while new ones are introduced).
    Yields (n_tasks,) float32 one-hot weight vectors to feed as
    "task_weight" when the program is built with
    dynamic_task_weights=True."""
    import numpy as np
    w = np.asarray(weights, np.float64)
    p = w / w.sum()
    rng = np.random.RandomState(seed)
    for _ in range(int(n_steps)):
        vec = np.zeros(len(weights), np.float32)
        vec[rng.choice(len(weights), p=p)] = 1.0
        yield vec


def ernie2_multitask_program(cfg, batch_size, seq_len, max_preds_per_seq=20,
                             num_sent_classes=3, num_ir_classes=3,
                             task_weights=(1.0, 1.0, 1.0),
                             optimizer_fn=None, is_test=False,
                             dynamic_task_weights=False):
    """Three representative ERNIE-2.0 tasks on one shared encoder:
      1. masked LM (word-aware, knowledge masking comes from the data gen)
      2. sentence-reorder classification on [CLS] (structure-aware)
      3. IR relevance classification on [CLS] (semantic-aware)
    Feeds add task_ids (N,T,1) — the task-id embedding of ERNIE 2.0.
    dynamic_task_weights=True adds a "task_weight" (3,) float32 feed (see
    ernie2_task_schedule) so the task-sampling schedule drives per-step
    loss mixing without recompiling.
    """
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        src_ids = layers.data("src_ids", [seq_len, 1], dtype="int64")
        pos_ids = layers.data("pos_ids", [seq_len, 1], dtype="int64")
        sent_ids = layers.data("sent_ids", [seq_len, 1], dtype="int64")
        task_ids = layers.data("task_ids", [seq_len, 1], dtype="int64")
        input_mask = layers.data("input_mask", [seq_len, 1],
                                 dtype="float32")
        mask_pos = layers.data("mask_pos", [1], dtype="int64")
        mask_label = layers.data("mask_label", [1], dtype="int64")
        reorder_label = layers.data("reorder_label", [1], dtype="int64")
        ir_label = layers.data("ir_label", [1], dtype="int64")

        # task-id embedding joins the usual three embeddings
        seq_out, pooled = bert_encoder(src_ids, pos_ids, sent_ids,
                                       input_mask, cfg, is_test=is_test,
                                       task_ids=task_ids)

        flat = layers.reshape(seq_out, [-1, cfg.hidden_size])
        picked = layers.gather(flat, mask_pos)
        trans = layers.fc(picked, cfg.hidden_size, act="gelu",
                          param_attr=ParamAttr(name="mask_lm_trans_fc.w_0",
                                               initializer=_init(cfg)),
                          bias_attr=ParamAttr(name="mask_lm_trans_fc.b_0"))
        trans = layers.layer_norm(
            trans, begin_norm_axis=1,
            param_attr=ParamAttr(name="mask_lm_trans_ln_s"),
            bias_attr=ParamAttr(name="mask_lm_trans_ln_b"))
        word_emb = main.global_block().var("word_embedding")
        mlm_bias = layers.create_parameter(
            [cfg.vocab_size], "float32", name="mask_lm_out_fc.b_0",
            default_initializer=pt.initializer.Constant(0.0))
        # fused head (see bert_pretrain_program): logits never leave the op
        mlm_loss = layers.mean(layers.fused_mlm_head_loss(
            trans, word_emb, mask_label, bias=mlm_bias,
            cast_bf16=cfg.dtype == "bfloat16"))

        def _cls_head(name, n_cls, label):
            logits = layers.fc(
                pooled, n_cls,
                param_attr=ParamAttr(name=name + ".w_0",
                                     initializer=_init(cfg)),
                bias_attr=ParamAttr(name=name + ".b_0"))
            return layers.mean(
                layers.softmax_with_cross_entropy(logits, label))

        reorder_loss = _cls_head("task_reorder_fc", num_sent_classes,
                                 reorder_label)
        ir_loss = _cls_head("task_ir_fc", num_ir_classes, ir_label)

        if dynamic_task_weights:
            tw = layers.data("task_weight", [3], dtype="float32",
                             append_batch_size=False)
            parts = []
            for i, task_loss in enumerate((mlm_loss, reorder_loss,
                                           ir_loss)):
                wi = layers.slice(tw, axes=[0], starts=[i], ends=[i + 1])
                parts.append(layers.elementwise_mul(task_loss, wi))
            loss = layers.elementwise_add(
                layers.elementwise_add(parts[0], parts[1]), parts[2])
        else:
            w = task_weights
            loss = layers.scale(mlm_loss, scale=float(w[0]))
            loss = layers.elementwise_add(
                loss, layers.scale(reorder_loss, scale=float(w[1])))
            loss = layers.elementwise_add(
                loss, layers.scale(ir_loss, scale=float(w[2])))
        if optimizer_fn is not None:
            optimizer_fn(loss)
    feeds = ["src_ids", "pos_ids", "sent_ids", "task_ids", "input_mask",
             "mask_pos", "mask_label", "reorder_label", "ir_label"]
    if dynamic_task_weights:
        feeds.append("task_weight")
    fetch = {"loss": loss, "mlm_loss": mlm_loss,
             "reorder_loss": reorder_loss, "ir_loss": ir_loss}
    return main, startup, feeds, fetch


def ernie2_synthetic_batch(cfg, batch_size, seq_len, max_preds_per_seq=20,
                           seed=0):
    import numpy as np
    b = synthetic_batch(cfg, batch_size, seq_len, max_preds_per_seq, seed)
    rng = np.random.RandomState(seed + 1)
    b["task_ids"] = np.zeros((batch_size, seq_len, 1), np.int64)
    b["reorder_label"] = rng.randint(0, 3, (batch_size, 1)).astype(np.int64)
    b["ir_label"] = rng.randint(0, 3, (batch_size, 1)).astype(np.int64)
    del b["labels"]
    return b
