"""Serving fleet — multi-replica router with continuous micro-batching
on the coordination plane.

Reference parity: the reference inference stack serves fleets of C++
predictors behind load balancers (analysis_predictor + Anakin/TensorRT
deployments); our port's :class:`~.serving.ServingPredictor` is one
replica in one process. This module is the fleet story: N predictor
replicas run as heartbeat-leased members of the PR 5
:class:`~.framework.transport.CoordServer` plane, and a stdlib-HTTP
router (the ``resilience.serve_metrics`` style — no dependencies)
fronts them with continuous micro-batching.

Topology (one coordination group of ``n_replicas + n_routers`` hosts,
growable by dynamic resize):

  host 0..N-1     :class:`ReplicaMember` — loads the StableHLO serving
                  artifact, serves ``POST /infer`` over HTTP,
                  heartbeats the CoordServer (its liveness lease), and
                  runs the lockstep *control rounds* that agree
                  admissions.
  host N..N+R-1   :class:`FleetRouter` x R — the replicated front
                  door. Each router serves ``/infer`` independently
                  (clients rotate across them — :class:`FleetClient`);
                  every router is a full group member (it heartbeats,
                  joins control rounds), which is what makes a
                  single-replica fleet's restart admissible: a router
                  is always a survivor that can vote the joiner in.
  host >= N+R     replicas GROWN at runtime: the Autoscaler resizes
                  the group (``CoordServer`` ``resize`` op — new slots
                  are born fenced) and the spawned replica joins
                  through the ordinary announce/admit/join path.

Router HA (the PR 11 tier): admission stays frozen-verdict-based, but
it is ENACTED (the joiner un-fenced) only by the **admission leader**
— the lowest live router id, judged from the heartbeat leases. Leader
changes are term-stamped in the member registry (each router's info
blob carries ``lterm``): a takeover bumps the term past every
observed claim, incumbency is sticky (a restarted ex-leader rejoins
as a FOLLOWER), and a stale ex-leader's enactment is refused by the
term check it runs against the registry at enact time — the PR 9
transport term-fencing discipline, re-hosted one layer up. Replicas
enact only when NO router holds a live-looking lease (the router-less
degraded fleet). Routers also share their per-replica in-flight
counts through their info blobs, so N routers' least-loaded dispatch
judges the REAL per-replica load, not each router's own slice — and a
failed-over request does not double-count.

Data plane (router):

  * **Continuous micro-batching.** In-flight requests are coalesced in
    arrival order up to ``max_batch`` request-batch rows or until the
    oldest request has waited ``batch_deadline_s``, whichever first;
    the coalesced feed rides ONE ``/infer`` call (list concatenation
    along the batch dim, split back per caller by the export's
    recorded batch factors — never guessed from runtime shapes).
  * **Least-loaded dispatch.** The routing table derives from the
    CoordServer ``members`` snapshot (registered info blobs minus the
    lost map); the live replica with the fewest router-dispatched
    batches in flight wins, equally-loaded replicas rotating
    round-robin so no healthy replica is ever shadowed.
  * **Shed / degrade.** A full router queue sheds with
    :class:`~.framework.resilience.ServerOverloadedError` (HTTP 503);
    per-replica policies (in-flight caps, cold-bucket degradation)
    keep working unchanged — a replica-side 503 is retried on a
    sibling, and only when every live replica sheds does the caller
    see 503.
  * **Retry on a sibling.** A dispatch that dies mid-flight
    (connection reset = SIGKILLed replica, replica 5xx) is retried on
    the least-loaded untried sibling within the request deadline, so
    a replica death costs zero failed requests — not even the ones in
    flight on it.

Control plane (the elastic path, verbatim from training):

  * **Replica death.** The heartbeat lease fences it (CoordServer's
    deadline monitor — nobody declares anything); the router's next
    members poll drops it from rotation and in-flight work re-routes.
  * **Restart.** The fresh process finds itself fenced and re-admits
    through the full ``announce_join``/``admit``/``join`` protocol:
    survivors observe the pending set on their next control round and
    all admit the same joiner from the same frozen verdicts — the
    ElasticTrainer window-boundary admission, re-hosted.
  * **Rolling weight refresh.** ``FleetRouter.rolling_deploy(dir)``
    drains ONE replica at a time: the replica fences itself (a
    planned loss, the ``drain_after`` shape), reloads + warms the new
    artifact while its HTTP server keeps answering (in-flight work
    completes on the old weights — zero dropped traffic), then
    rejoins through the same admission. The artifact movement is
    accounted like the rejoin state-ship: raw vs zlib-wire bytes land
    in ``resilience.bytes_totals()["stateship"]``.

Observability (rides ``resilience.metrics()`` — see the router series
there): ``router_requests_total{outcome=}``,
``router_retries_total{replica=}``, ``router_batch_size`` histogram,
``router_queue_depth`` and per-replica ``router_replica_inflight``
gauges — all cumulative counters outside the bounded event log, since
requests (and shed-storm retries) run at request rate. Rare
control-plane transitions (a connection-level ``router_retry``,
``fleet_deploy_*``, ``fleet_rejoin*``) ride the ordinary event log.
``tools/serving_probe.py --metrics-url`` folds the ``router_*``
series under a ``"router"`` group.

Deploy via ``tools/servingsvc.py`` (one ``replica`` process per
replica, one ``router``), against a ``tools/coordsvc.py`` service —
``--n-hosts auto`` learns the group size from the first member, and
``--hb-deadline-s`` MUST be armed (fleet liveness is the lease).

Coordination-plane HA: ``coord_address`` accepts a LIST of endpoints
(``"h:p0,h:p1"`` or a list) — a term-replicated coordsvc group
(``--peers`` mode). Every member's SocketCoordinator/CoordClient then
fails over transparently to the promoted standby, so a coordinator
SIGKILL — even mid rolling-deploy — fences nobody, drops no traffic
and aborts no admission: the fleet battery asserts exactly that.
"""
import collections
import json
import os
import random
import threading
import time
import zlib

from .framework import faultinject
from .framework import obs
from .framework import resilience
from .framework.coordination import (CoordinationError, HostLostError,
                                     SocketCoordinator, agreed_pending)
from .framework.resilience import (DeadlineExceededError,
                                   ServerOverloadedError, record_event)

__all__ = ["FleetError", "FleetRouter", "ReplicaMember", "FleetClient",
           "Autoscaler", "router_host_id", "http_json"]


class FleetError(RuntimeError):
    """A fleet-level operation failed (deploy step, no live replica at
    start, a member that could not be admitted)."""


def router_host_id(n_replicas, router_id=0):
    """Router ``router_id``'s host id in the coordination group: base
    replicas are hosts ``0..N-1``, routers ``N..N+R-1`` (grown
    replicas, if any, sit above the router range)."""
    return int(n_replicas) + int(router_id)


# ---------------------------------------------------------------------------
# tiny JSON-over-HTTP wire helpers (stdlib only)
# ---------------------------------------------------------------------------

def http_json(method, url, payload=None, timeout_s=10.0, headers=None):
    """One JSON request/response round trip. Returns ``(status,
    dict)`` — non-2xx responses are returned, not raised, so callers
    can route on replica-side shed (503) vs deadline (504) vs error.
    Connection-level failures (dead process, refused) raise OSError.
    ``headers`` adds/overrides request headers (the trace-context
    ``x-trace-id`` rides here)."""
    import urllib.error
    import urllib.request
    data = None if payload is None else json.dumps(payload).encode()
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(
        url, data=data, method=method, headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = resp.read().decode() or "{}"
            return resp.status, json.loads(body)
    except urllib.error.HTTPError as e:
        body = e.read().decode() if e.fp is not None else ""
        try:
            parsed = json.loads(body) if body else {}
        except ValueError:
            parsed = {"error": body}
        return e.code, parsed
    except urllib.error.URLError as e:
        # unwrap to the OSError the retry path classifies on
        reason = getattr(e, "reason", e)
        raise reason if isinstance(reason, OSError) \
            else ConnectionError(str(e))


def _start_http(handler_cls, host, port, name):
    import http.server

    class _Server(http.server.ThreadingHTTPServer):
        # the stdlib default listen backlog of 5 collapses a
        # connection-per-request burst: overflowed SYNs retransmit
        # after a full second, so a 24-client surge reaches the
        # router ~2 requests at a time and its queue/shed load
        # signals never see the pressure that is actually there
        request_queue_size = 128
        daemon_threads = True

    srv = _Server((host, port), handler_cls)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name=name)
    t.start()
    return srv, t


def _live_peers(co, self_id):
    """Un-fenced members with a live-looking lease, excluding
    ``self_id``. A lease older than the server's fencing deadline is
    a leftover from a cleanly-closed member (the hb map never forgets)
    — counting it as a survivor would make a member self-fence for a
    peer that cannot admit it back. Empty on a coordinator error."""
    try:
        m = co.members()
    except (CoordinationError, ConnectionError):
        return []
    deadline = m.get("hb_deadline_s")
    return [h for h, age in m["hb_age"].items()
            if h != self_id and h not in m["lost"]
            and (deadline is None or age <= deadline)]


def _artifact_wire_bytes(dirname, compress="zlib"):
    """(raw, wire) byte sizes of the serving artifact under
    ``dirname`` — the rolling-refresh twin of the rejoin state-ship
    accounting. ``raw`` is the on-disk artifact; ``wire`` is what a
    zlib transport would move (== raw when compress is None)."""
    from .serving import MODULE_SUBDIR
    root = os.path.join(dirname, MODULE_SUBDIR)
    raw = wire = 0
    for fname in sorted(os.listdir(root)):
        path = os.path.join(root, fname)
        if not os.path.isfile(path):
            continue
        size = os.path.getsize(path)
        raw += size
        if compress == "zlib":
            comp = zlib.compressobj(6)
            n = 0
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    n += len(comp.compress(chunk))
            wire += n + len(comp.flush())
        else:
            wire += size
    return raw, wire


# ---------------------------------------------------------------------------
# shared control-plane engine (router and replicas are both members)
# ---------------------------------------------------------------------------

class _FleetMember(object):
    """One heartbeat-leased member of the fleet's coordination group.

    Owns the :class:`SocketCoordinator` (hello + liveness lease) and
    the lockstep *control rounds*: every ``ctl_interval_s`` each live
    member gathers ``["ok", pending_joins]`` under a shared round
    counter, so all of them compute the same admission from the same
    frozen verdicts — the ElasticTrainer window-boundary agreement,
    without a training loop to ride on. A member that finds itself
    fenced (SIGKILL restart, deploy self-fence, a heartbeat stall)
    takes the announce/join path and adopts the survivors' round
    counter from the admission sync value, so round names never
    collide across incarnations."""

    def __init__(self, coord_address, n_replicas, host_id,
                 ctl_interval_s=0.1, hb_interval_s=0.25,
                 timeout_s=30.0, join_timeout_s=30.0, poll_s=0.005,
                 n_routers=1, group_size=None):
        if int(n_replicas) < 1:
            raise ValueError("a fleet needs n_replicas >= 1")
        if int(n_routers) < 1:
            raise ValueError("a fleet needs n_routers >= 1")
        self._coord_address = coord_address
        self.n_replicas = int(n_replicas)
        self.n_routers = int(n_routers)
        # group_size covers GROWN fleets: base replicas 0..N-1, routers
        # N..N+R-1, dynamically grown replicas above — a grown member
        # must hello with the group's CURRENT (post-resize) size
        self.group_size = int(group_size) if group_size is not None \
            else self.n_replicas + self.n_routers
        if self.group_size < self.n_replicas + self.n_routers:
            raise ValueError(
                "group_size %d is smaller than the base layout "
                "(%d replicas + %d routers)"
                % (self.group_size, self.n_replicas, self.n_routers))
        self._host_id = int(host_id)
        self._ctl_interval_s = float(ctl_interval_s)
        self._hb_interval_s = float(hb_interval_s)
        self._timeout_s = float(timeout_s)
        self._join_timeout_s = float(join_timeout_s)
        self._poll_s = float(poll_s)
        self._co = None
        self._k = 0
        self._stop = threading.Event()
        self._threads = []

    # -- subclass surface --------------------------------------------------
    def _prepare(self):
        """Bring the serving surface up BEFORE joining the group (a
        member must never advertise what it cannot serve)."""

    def _after_join(self):
        """Start whatever needs the live coordinator (pollers)."""

    def _sync_value(self):
        """This member's contribution to an admission round:
        ``[round_k, generation, artifact_dir]``. The joiner adopts the
        lexicographic max, so the router (no artifact) contributes
        generation -1 and defers to any replica's value."""
        return [self._k, -1, ""]

    def _adopt_sync(self, sync):
        self._k = int(sync[0])

    def _publish_info(self):
        """Publish this member's registry blob (``put_info``)."""

    # -- lifecycle ---------------------------------------------------------
    def _preflight_supersede(self):
        """A QUICK restart — before the previous incarnation's lease
        was fenced — must not start control rounds at counter 0 while
        the survivors sit at N: the desynced round names would wedge
        both sides' gathers. If the server holds a live-looking lease
        for this host id, fence it (supersede the dead incarnation)
        so this start takes the ordinary rejoin path and ADOPTS the
        survivors' counter from the admission sync.

        Returns the server's CURRENT group size (or ``None`` before
        the first sized hello / when unreachable): a restart after an
        autoscale resize must hello with the group's live size, not
        the base layout its command line froze at boot."""
        from .framework.transport import CoordClient
        server_size = None
        try:
            client = CoordClient(self._coord_address,
                                 host_id=self._host_id)
            try:
                resp = client.call("members")
                server_size = resp.get("n_hosts")
                has_lease = str(self._host_id) in resp.get("hb_age", {})
                fenced = str(self._host_id) in resp.get("lost", {})
                if has_lease and not fenced:
                    client.call("mark_lost",
                                reason="superseded: new incarnation "
                                "of member %d" % self._host_id)
                    record_event("fleet_supersede",
                                 member=self._host_id)
            finally:
                client.close()
        except (RuntimeError, OSError):
            # auto-size server before its first hello, or coordinator
            # unreachable: nothing to supersede — first-boot path
            pass
        return server_size

    def start(self):
        self._prepare()
        try:
            server_size = self._preflight_supersede()
            if server_size is not None \
                    and int(server_size) != self.group_size \
                    and int(server_size) \
                    >= self.n_replicas + self.n_routers:
                # the server's size is authoritative — a base member
                # restarted after an autoscale grow/shrink would
                # otherwise hello with its frozen boot-time size and
                # be refused with the RESIZED mismatch error forever
                record_event("fleet_adopt_group_size",
                             member=self._host_id,
                             configured=self.group_size,
                             adopted=int(server_size))
                self.group_size = int(server_size)
            # detect_loss=False: fleet liveness is EXCLUSIVELY the
            # heartbeat lease (the server monitor). Client-driven
            # fencing at gather deadlines is a training-plane fallback
            # that, on a desynced or wedged member, would mark_lost
            # every healthy peer — a timeout here surfaces as
            # BarrierTimeoutError and the tick simply retries.
            self._co = SocketCoordinator(
                self._coord_address, self.group_size,
                self._host_id, timeout_s=self._timeout_s,
                poll_s=self._poll_s, mesh_reinit=False,
                detect_loss=False, hb_interval_s=self._hb_interval_s)
            if self._host_id in self._co.lost_hosts():
                # a restarted incarnation: fenced by the previous
                # one's stale lease (or the preflight supersede) —
                # re-admit through the full protocol before taking
                # any traffic-facing role
                if not self._rejoin() and not self._solo_recover():
                    raise FleetError(
                        "member %d is fenced and was not admitted "
                        "within %.1fs — are the survivors (or the "
                        "router) up?"
                        % (self._host_id, self._join_timeout_s))
            self._publish_info()
            self._after_join()
            t = threading.Thread(target=self._control_loop,
                                 daemon=True,
                                 name="paddle_tpu-fleet-ctl-%d"
                                 % self._host_id)
            t.start()
            self._threads.append(t)
        except BaseException:
            # full teardown on ANY start failure (coordinator
            # unreachable, pod-size mismatch, not admitted):
            # _prepare() already bound the HTTP listener and threads,
            # and a supervisor retry loop must not accumulate one
            # live listener per failed attempt
            self.close()
            raise
        return self

    def _solo_recover(self):
        """Last member standing: a fenced member with NO other
        live-looking member has nobody to admit it — with nothing
        live there is no split brain to protect against either, so it
        un-fences itself and restarts the control plane fresh."""
        try:
            if _live_peers(self._co, self._host_id):
                return False
            self._co.unfence(self._host_id)
            record_event("fleet_solo_recover", member=self._host_id)
            return True
        except (CoordinationError, ConnectionError):
            return False

    def close(self):
        self._stop.set()
        # the client goes first: a control thread blocked in a gather
        # sees the closed transport raise and exits on the stop flag
        # instead of riding out a full round timeout
        if self._co is not None:
            self._co.close()
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- the control rounds ------------------------------------------------
    def _control_loop(self):
        while not self._stop.is_set():
            try:
                self._ctl_tick()
            except Exception as e:   # noqa: BLE001 - the loop IS the
                # member's control plane: an unexpected error must cost
                # one tick, never the thread (a replica with no control
                # loop can never rejoin and wedges future admissions)
                record_event("fleet_ctl_error", member=self._host_id,
                             error=type(e).__name__)
            self._stop.wait(self._ctl_interval_s)

    def _ctl_tick(self):
        """One lockstep control round. Always returns True (the loop
        runs until close): a FENCED member attempts a rejoin and, when
        not admitted this attempt (a coordinator blip, survivors
        mid-recovery, a router restart), simply RETRIES next tick —
        a transient fence must never strand a serving member out of
        rotation for the life of the process."""
        co = self._co
        try:
            pending = sorted([int(h), int(n)] for h, n
                             in co.pending_joins().items())
        except (CoordinationError, ConnectionError):
            return True     # coordinator unreachable: serve on, retry
        self._k += 1
        try:
            verdicts = co.all_gather("ctl%d" % self._k, self._host_id,
                                     ["ok", pending])
        except HostLostError:
            record_event("fleet_fenced", member=self._host_id)
            if not self._rejoin():
                # nobody admitted us this attempt; if nobody live is
                # LEFT to admit (a 1-replica fleet whose router died),
                # recover solo — otherwise the next tick retries
                self._solo_recover()
            return True
        # admission from the frozen verdicts: every member meets the
        # SAME admission barrier for the first pending pair EVERY
        # participant observed — identical on all of them, so the join
        # barrier always completes (the invariant is shared with
        # ElasticTrainer's window admission). The un-fence itself is
        # ENACTED only by the admission leader (lowest live router id,
        # term-stamped — see FleetRouter._admission_enactor); everyone
        # else follows the barrier once the enactment lands.
        agreed = agreed_pending(verdicts)
        if agreed is not None:
            try:
                sync = co.admit(self._host_id, agreed[0], agreed[1],
                                self._sync_value(), name="fjoin",
                                timeout_s=self._join_timeout_s,
                                enact=self._admission_enactor())
                if sync is not None:
                    record_event("fleet_admit", member=self._host_id,
                                 joined=agreed[0])
            except HostLostError:
                record_event("fleet_fenced", member=self._host_id)
                if not self._rejoin():
                    self._solo_recover()
            except (CoordinationError, ConnectionError):
                return True
        return True

    def _admission_enactor(self):
        """Whether THIS member ENACTS (un-fences) the agreed admission.
        Base policy (replicas): only when no router holds a
        live-looking lease — the admission leader (lowest live router
        id) enacts, and replicas are the fallback for a router-less
        degraded fleet; FleetRouter overrides with the term-stamped
        leader check."""
        try:
            m = self._co.members()
        except (CoordinationError, ConnectionError):
            return True      # cannot judge: enacting is the safe side
        dl = m.get("hb_deadline_s")
        for h, info in m["info"].items():
            if isinstance(info, dict) and info.get("kind") == "router" \
                    and h not in m["lost"]:
                age = m["hb_age"].get(h)
                if age is not None and (dl is None or age <= dl):
                    return False
        return True

    def _rejoin(self):
        """Fenced-member tail: announce, wait for the survivors'
        admission, adopt their round counter (and, for replicas, the
        fleet's current artifact). Returns False when not admitted —
        the member stays out and the orchestrator escalates."""
        co = self._co
        nonce = random.getrandbits(31)
        try:
            co.announce_join(self._host_id, nonce)
            record_event("fleet_rejoin_announce", member=self._host_id,
                         nonce=nonce)
            sync = co.join(self._host_id, nonce, name="fjoin",
                           timeout_s=self._join_timeout_s)
        except (CoordinationError, ConnectionError) as e:
            record_event("fleet_rejoin_failed", member=self._host_id,
                         error=type(e).__name__)
            return False
        self._adopt_sync(sync)
        self._publish_info()
        record_event("fleet_rejoin", member=self._host_id)
        return True


# ---------------------------------------------------------------------------
# replica
# ---------------------------------------------------------------------------

class ReplicaMember(_FleetMember):
    """One serving replica: a :class:`~.serving.ServingPredictor`
    behind a stdlib HTTP endpoint, registered as a heartbeat-leased
    member of the fleet's coordination group.

    Endpoints:
      ``POST /infer``           {"feeds": {name: rows}, "deadline_s"?}
                                -> {"outputs", "dtypes", "replica",
                                "generation"}; 503 on the predictor's
                                in-flight shed, 504 on its deadline
      ``GET /healthz``          ServingPredictor.health() + identity
      ``GET /meta``             the export contract the router batches
                                by (feed names/factors/dtypes, buckets)
      ``POST /admin/refresh``   {"dir": artifact_dir} — queue the
                                rolling-deploy weight refresh (the
                                control thread executes it: self-fence,
                                reload + warm, rejoin)

    The per-replica policies are the predictor's own (``max_in_flight``
    load shed, ``deadline_s``, warm-bucket degradation) — the router
    composes with them, never replaces them."""

    def __init__(self, artifact_dir, coord_address, n_replicas,
                 replica_id, port=0, host="127.0.0.1", warmup=True,
                 max_in_flight=None, deadline_s=None,
                 ship_compress="zlib", artifact_compress=None,
                 ctl_interval_s=0.1,
                 hb_interval_s=0.25, timeout_s=30.0,
                 join_timeout_s=30.0, n_routers=1, group_size=None):
        rid = int(replica_id)
        gs = int(group_size) if group_size is not None \
            else int(n_replicas) + int(n_routers)
        router_lo = int(n_replicas)
        router_hi = int(n_replicas) + int(n_routers)
        # valid replica slots: the base tier below the routers, plus
        # dynamically GROWN slots above them (group resize)
        if not (0 <= rid < router_lo or router_hi <= rid < gs):
            raise ValueError(
                "replica_id %r is not a replica slot (%d base "
                "replicas, routers %d..%d, group size %d)"
                % (replica_id, n_replicas, router_lo, router_hi - 1,
                   gs))
        super(ReplicaMember, self).__init__(
            coord_address, n_replicas, rid,
            ctl_interval_s=ctl_interval_s, hb_interval_s=hb_interval_s,
            timeout_s=timeout_s, join_timeout_s=join_timeout_s,
            n_routers=n_routers, group_size=group_size)
        if ship_compress not in (None, "zlib"):
            raise ValueError("ship_compress must be None or 'zlib', "
                             "got %r" % (ship_compress,))
        if artifact_compress not in (None, "q8"):
            raise ValueError("artifact_compress must be None or 'q8', "
                             "got %r" % (artifact_compress,))
        self.replica_id = int(replica_id)
        self._artifact_dir = str(artifact_dir)
        self._http_host = host
        self._http_port = int(port)
        self._warmup = bool(warmup)
        self._max_in_flight = max_in_flight
        self._deadline_s = deadline_s
        self._ship_compress = ship_compress
        self._artifact_compress = artifact_compress
        # deadline-budget guard counter: dispatched work refused
        # because its x-deadline-ms budget was already spent on
        # arrival. The router checks remaining budget immediately
        # before every send, so a live fleet holds this at ZERO — the
        # soak test counter-asserts it (a nonzero value means a
        # request WAS dispatched after expiry)
        self._expired_refused = 0
        self._pred = None
        self._pred_lock = threading.Lock()
        self._generation = 0
        self._refresh_req = None
        self._refresh_lock = threading.Lock()
        self._draining = False
        self._server = None
        self.address = None

    # -- serving surface ---------------------------------------------------
    def _prepare(self):
        # name this process's span dumps (deployment env wins)
        obs.set_service("replica%d" % self.replica_id, force=False)
        self._load_predictor(self._artifact_dir, account=False)
        member = self
        import http.server

        class _Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, status, payload):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):   # noqa: N802 - stdlib naming
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    self._send(400, {"error": "malformed JSON body"})
                    return
                path = self.path.split("?", 1)[0]
                if path == "/infer":
                    # the serve span adopts the router's (or a direct
                    # caller's) trace context from the x-trace-id
                    # header — the replica leg of the one-request
                    # timeline
                    tr, parent = obs.parse_header(
                        self.headers.get("x-trace-id"))
                    tenant = self.headers.get("x-tenant") \
                        or body.get("tenant") or "default"
                    with obs.span("replica.serve", trace_id=tr,
                                  parent=parent,
                                  replica=member.replica_id,
                                  generation=member.generation,
                                  tenant=tenant) as sp:
                        status, payload = member._handle_infer(
                            body, tenant=tenant,
                            deadline_ms=self.headers.get(
                                "x-deadline-ms"))
                        sp.set(status=status)
                    self._send(status, payload)
                elif path == "/admin/refresh":
                    new_dir = body.get("dir")
                    if not new_dir:
                        self._send(400, {"error": "refresh needs "
                                         '{"dir": artifact_dir}'})
                        return
                    if member.request_refresh(new_dir):
                        self._send(200, {"ok": True, "queued": new_dir})
                    else:
                        self._send(409, {"error": "a refresh is "
                                         "already queued"})
                elif path == "/admin/drain":
                    if member.drain():
                        self._send(200, {"ok": True, "draining": True})
                    else:
                        self._send(503, {"error": "drain could not "
                                         "reach the coordinator — "
                                         "retry"})
                else:
                    self._send(404, {"error": "try /infer"})

            def do_GET(self):    # noqa: N802 - stdlib naming
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    self._send(200, member.health())
                elif path == "/meta":
                    self._send(200, member.meta())
                elif path == "/admin/trace":
                    # live span pull: tools/traceview.py merges these
                    # across fleet members into one timeline
                    self._send(200, obs.dump_dict())
                else:
                    self._send(404, {"error": "try /healthz or /meta"})

            def log_message(self, *args):   # requests are not log lines
                pass

        self._server, t = _start_http(
            _Handler, self._http_host, self._http_port,
            "paddle_tpu-replica-%d" % self.replica_id)
        self._threads.append(t)
        self.address = "%s:%d" % self._server.server_address[:2]

    def _load_predictor(self, dirname, account=True, gen=None):
        """Load + warm a predictor from ``dirname`` and swap it in.
        ``account=True`` (every refresh after the first) records the
        artifact movement as state-ship bytes, the rolling-deploy twin
        of the elastic rejoin ship. ``gen`` pins the generation (a
        rejoiner adopting the fleet's current artifact takes the
        fleet's generation, not its own +1)."""
        from .serving import ServingPredictor
        pred = ServingPredictor(dirname,
                                max_in_flight=self._max_in_flight,
                                deadline_s=self._deadline_s)
        if self._artifact_compress == "q8" \
                and pred.weight_compress != "q8":
            # deploy-time guard: a replica provisioned for quantized
            # artifacts (the shrunken ship-bytes budget) must refuse a
            # full-precision artifact at LOAD, not discover the 4x
            # state-ship blowup on its next rolling deploy
            raise FleetError(
                "replica %d runs with artifact_compress='q8' but %s "
                "is a full-precision export — re-export it with "
                "weight_compress='q8'" % (self.replica_id, dirname))
        if self._warmup:
            pred.warmup()
        if account:
            try:
                raw, wire = _artifact_wire_bytes(dirname,
                                                 self._ship_compress)
                resilience.record_bytes("stateship", raw, wire)
            except OSError:   # accounting must never fail a deploy
                pass
        with self._pred_lock:
            self._pred = pred
            self._artifact_dir = str(dirname)
            self._generation = self._generation + 1 if gen is None \
                else int(gen)

    def _predictor(self):
        with self._pred_lock:
            return self._pred

    @property
    def generation(self):
        with self._pred_lock:
            return self._generation

    def health(self):
        pred = self._predictor()
        snap = pred.health()
        with self._pred_lock:
            expired_refused = self._expired_refused
        snap.update({"replica": self.replica_id,
                     "generation": self.generation,
                     "artifact_dir": self._artifact_dir,
                     "expired_refused": expired_refused})
        return snap

    def meta(self):
        pred = self._predictor()
        return {"feed_names": pred.get_input_names(),
                "fetch_names": pred.get_output_names(),
                "feed_batch_factors": pred.feed_batch_factors(),
                "fetch_batch_factors": pred.fetch_batch_factors(),
                "feed_dtypes": pred.feed_dtypes(),
                "feed_inner_shapes": pred.feed_inner_shapes(),
                "dynamic_batch": pred.dynamic_batch,
                "max_bucket": pred.max_bucket}

    def _handle_infer(self, body, tenant=None, deadline_ms=None):
        import numpy as np
        pred = self._predictor()
        feeds_json = body.get("feeds")
        if not isinstance(feeds_json, dict):
            return 400, {"error": 'infer needs {"feeds": {name: rows}}'}
        deadline_s = body.get("deadline_s")
        if deadline_s is not None:
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError):
                return 400, {"error": "deadline_s must be a number, "
                             "got %r" % (deadline_s,)}
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
            except (TypeError, ValueError):
                return 400, {"error": "x-deadline-ms must be a "
                             "number, got %r" % (deadline_ms,)}
            if deadline_ms <= 0:
                # the propagated budget is already SPENT: refuse
                # before the predictor burns a batch slot — the
                # caller's _finish_pending gave up long ago, so any
                # work here is pure waste (satellite guard; the
                # "replica" series must stay 0 in a healthy fleet)
                with self._pred_lock:
                    self._expired_refused += 1
                resilience.record_router_expired("replica",
                                                 tenant=tenant)
                return 504, {"error": "deadline budget exhausted "
                             "before serving — refused without "
                             "entering the batch window",
                             "kind": "deadline"}
            budget_s = deadline_ms / 1000.0
            deadline_s = budget_s if deadline_s is None \
                else min(deadline_s, budget_s)
        dtypes = pred.feed_dtypes()
        try:
            feeds = {n: np.asarray(v, dtype=np.dtype(dtypes[n]))
                     for n, v in feeds_json.items() if n in dtypes}
            # an injected raise surfaces as this replica's 500 — the
            # router treats it like any replica fault and retries the
            # batch on a sibling (``host`` filter = this replica's id)
            feeds = faultinject.hit("serving.infer", feeds,
                                    host=self.replica_id)
            if feeds is faultinject.DROP:
                raise RuntimeError("serving.infer: request dropped by "
                                   "failpoint")
            outs = pred.run(feeds, deadline_s=deadline_s)
        except ServerOverloadedError as e:
            return 503, {"error": str(e), "kind": "overloaded"}
        except DeadlineExceededError as e:
            return 504, {"error": str(e), "kind": "deadline"}
        except Exception as e:
            return 500, {"error": "%s: %s" % (type(e).__name__, e),
                         "kind": "error"}
        outs = [np.asarray(o) for o in outs]
        return 200, {"outputs": [o.tolist() for o in outs],
                     "dtypes": [str(o.dtype) for o in outs],
                     "replica": self.replica_id,
                     "generation": self.generation}

    # -- control plane -----------------------------------------------------
    def _publish_info(self, ready=True):
        try:
            self._co.put_info({"kind": "replica", "addr": self.address,
                               "gen": self.generation,
                               "dir": self._artifact_dir,
                               "ready": bool(ready)})
        except (CoordinationError, ConnectionError):
            pass   # the next publish (rejoin/deploy) retries

    def _sync_value(self):
        return [self._k, self.generation, self._artifact_dir]

    def _adopt_sync(self, sync):
        self._k = int(sync[0])
        sync_gen = int(sync[1]) if len(sync) > 1 else -1
        sync_dir = sync[2] if len(sync) > 2 else ""
        # The admission sync orders by round counter FIRST, so it can
        # carry a router-only survivor's [k, -1, ""] (1-replica fleet)
        # or a counter-leading member's lagging artifact view. The
        # member registry holds every replica's last published
        # (gen, dir) — including THIS id's previous incarnation — so
        # the fleet's true current artifact is the max over both.
        try:
            m = self._co.members()
            for info in m["info"].values():
                if isinstance(info, dict) \
                        and info.get("kind") == "replica" \
                        and info.get("dir") \
                        and int(info.get("gen") or -1) > sync_gen:
                    sync_gen = int(info["gen"])
                    sync_dir = info["dir"]
        except (CoordinationError, ConnectionError):
            pass
        # adopt the fleet's artifact only when it is genuinely NEWER
        # (a higher fleet generation): a deploy-refreshed replica
        # rejoining must not be flipped BACK to the survivors' not-yet-
        # refreshed artifact by its own admission sync
        if sync_dir and sync_gen > self.generation \
                and sync_dir != self._artifact_dir \
                and os.path.isdir(sync_dir):
            try:
                self._load_predictor(sync_dir, gen=sync_gen)
                record_event("fleet_adopt", member=self._host_id,
                             generation=sync_gen)
            except Exception as e:
                record_event("fleet_adopt_failed", member=self._host_id,
                             error=type(e).__name__)

    def request_refresh(self, artifact_dir):
        """Queue a rolling-deploy weight refresh; the control thread
        executes it at its next tick (fence -> reload + warm -> rejoin
        — the HTTP server answers throughout, so in-flight and
        concurrent requests ride the old weights, never the floor).
        Returns False (HTTP 409) while another refresh is already
        queued — a racing second deploy must not silently overwrite
        the first (the test-and-set is locked against both a
        concurrent second request and the control thread's claim)."""
        with self._refresh_lock:
            if self._refresh_req is not None:
                return False
            self._refresh_req = str(artifact_dir)
        return True

    def drain(self):
        """PLANNED scale-in (the Autoscaler's shrink path): fence
        self, stop rejoining, and unpublish — in-flight requests still
        complete (the HTTP server keeps answering), but the routing
        tables drop this replica on their next poll and the drained
        slot can then be resized away. Returns False when the
        coordinator was unreachable (the caller retries)."""
        with self._refresh_lock:
            self._draining = True
        try:
            self._co.mark_lost(self._host_id,
                               "autoscale: drained for scale-in")
        except (CoordinationError, ConnectionError):
            with self._refresh_lock:
                self._draining = False
            return False
        self._publish_info(ready=False)
        record_event("fleet_drained", member=self._host_id)
        return True

    def _ctl_tick(self):
        with self._refresh_lock:
            if self._draining:
                # a drained member neither gathers nor rejoins: it is
                # leaving the group for good (the slot is resized away)
                return True
            req, self._refresh_req = self._refresh_req, None
        if req is not None:
            self._do_refresh(req)
            return True
        return super(ReplicaMember, self)._ctl_tick()

    def _other_live_members(self):
        """Un-fenced members with a LIVE-LOOKING lease besides this
        one — when empty (a one-replica fleet with no router, or the
        router cleanly shut down), the fence/rejoin dance has no
        survivor to admit us back, so a refresh swaps in place. A
        lease older than the server's own fencing deadline does not
        count: a cleanly-closed member's entry lingers in the hb map,
        and self-fencing on the strength of a peer that cannot admit
        would strand this replica."""
        return _live_peers(self._co, self._host_id)

    def _do_refresh(self, new_dir):
        record_event("fleet_deploy_begin", member=self._host_id,
                     dir=new_dir)
        survivors = self._other_live_members()
        if survivors:
            # a PLANNED loss (the drain shape): the router stops
            # routing here the moment its members poll sees the
            # tombstone; accepted work still completes
            try:
                self._co.mark_lost(self._host_id,
                                   "deploy: rolling weight refresh")
            except (CoordinationError, ConnectionError) as e:
                # coordinator unreachable: the admission protocol
                # cannot complete either — abort this refresh on the
                # OLD weights (the deploy driver times out and
                # reports) instead of fencing into a dead end
                record_event("fleet_deploy_failed",
                             member=self._host_id,
                             error=type(e).__name__)
                return
        try:
            self._load_predictor(new_dir)
        except Exception as e:
            record_event("fleet_deploy_failed", member=self._host_id,
                         error=type(e).__name__)
            # return to rotation on the OLD weights — a broken artifact
            # must degrade the deploy, not the fleet
            if survivors:
                self._rejoin()
            else:
                self._publish_info()
            return
        if survivors:
            if not self._rejoin():
                record_event("fleet_deploy_stranded",
                             member=self._host_id)
                return
        else:
            self._publish_info()
        record_event("fleet_deploy_done", member=self._host_id,
                     generation=self.generation)

    def close(self):
        if self._co is not None:
            self._publish_info(ready=False)
        # HTTP first: its serve_forever thread sits in _threads, and
        # the base close joins them — a still-serving listener would
        # ride out the whole join timeout
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        super(ReplicaMember, self).close()


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

DEFAULT_TENANT = "default"


class TenantClass(object):
    """One QoS class: the knobs a router schedules a tenant by.

    ``weight``       weighted-fair share of the batch cut (start-time
                     fair queuing — a weight-4 class drains 4x a
                     weight-1 class's rows under contention)
    ``priority``     brownout rank: under sustained overload the
                     router sheds the LOWEST live priority first; the
                     highest class is never floor-shed
    ``rate``/``burst``   token-bucket admission quota (requests/s,
                     bucket size; None = unmetered)
    ``max_inflight`` per-tenant cap on requests admitted and not yet
                     finished (None = uncapped)
    ``tenants``      explicit tenant ids mapped to this class; a
                     tenant naming no class maps by its own name,
                     else to the "default" class"""

    __slots__ = ("name", "weight", "priority", "rate", "burst",
                 "max_inflight", "tenants")

    def __init__(self, name, weight=1.0, priority=0, rate=None,
                 burst=None, max_inflight=None, tenants=()):
        self.name = str(name)
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError("tenant class %r needs weight > 0, got "
                             "%r" % (name, weight))
        self.priority = int(priority)
        self.rate = None if rate is None else float(rate)
        if self.rate is not None and self.rate <= 0:
            raise ValueError("tenant class %r needs rate > 0 (or "
                             "None), got %r" % (name, rate))
        if burst is not None:
            self.burst = float(burst)
        else:
            self.burst = None if self.rate is None \
                else max(1.0, self.rate)
        if self.burst is not None and self.burst < 1:
            raise ValueError("tenant class %r needs burst >= 1, got "
                             "%r" % (name, burst))
        self.max_inflight = None if max_inflight is None \
            else int(max_inflight)
        if self.max_inflight is not None and self.max_inflight < 1:
            raise ValueError("tenant class %r needs max_inflight >= 1"
                             " (or None), got %r"
                             % (name, max_inflight))
        self.tenants = frozenset(str(t) for t in tenants)


def parse_tenant_classes(spec):
    """{class_name: TenantClass} from a config mapping (or a list of
    dicts carrying "name") — the ``--tenant-classes`` JSON shape:

        {"gold":   {"weight": 4, "priority": 2},
         "silver": {"weight": 2, "priority": 1},
         "bronze": {"weight": 1, "priority": 0,
                    "rate": 50, "max_inflight": 8,
                    "tenants": ["batch-jobs", "crawler"]}}

    Empty/None disables QoS entirely (the router runs the classic
    single-FIFO path)."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        items = spec.items()
    else:
        items = [(c.get("name"), c) for c in spec]
    out = {}
    for name, cfg in items:
        if name is None:
            raise ValueError("tenant class list entries need a "
                             '"name" key')
        cfg = {k: v for k, v in dict(cfg or {}).items() if k != "name"}
        unknown = set(cfg) - {"weight", "priority", "rate", "burst",
                              "max_inflight", "tenants"}
        if unknown:
            raise ValueError("tenant class %r has unknown keys %s"
                             % (name, sorted(unknown)))
        out[str(name)] = TenantClass(name, **cfg)
    return out


class _Pending(object):
    __slots__ = ("feeds", "n", "deadline", "enqueued", "event",
                 "result", "error", "abandoned", "trace", "span",
                 "t_enq", "tenant", "retry_budget", "vstart",
                 "vfinish")

    def __init__(self, feeds, n, deadline, tenant=DEFAULT_TENANT,
                 retry_budget=None):
        self.feeds = feeds
        self.n = n
        self.deadline = deadline
        self.enqueued = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.abandoned = False
        # trace context (obs tentpole): the request's trace id, the
        # router serve span the queue/dispatch child spans parent
        # under, and the obs-time enqueue stamp the retroactive queue
        # span starts at — all None while tracing is off
        self.trace = None
        self.span = None
        self.t_enq = None
        # QoS context: the owning tenant, the bounded cross-hop retry
        # budget (None = unbounded, the historical behavior) and the
        # start-time-fair-queuing virtual tags the WFQ cut orders by
        self.tenant = tenant
        self.retry_budget = retry_budget
        self.vstart = 0.0
        self.vfinish = 0.0


class FleetRouter(_FleetMember):
    """The fleet's front door: continuous micro-batching over the live
    replica set.

    Endpoints:
      ``POST /infer``          same body as a replica's; coalesced,
                               dispatched, split back — the caller
                               cannot tell the fleet from one replica
      ``GET /healthz``         routing table + queue depth
      ``GET /metrics``         the live resilience exposition (router
                               series included)
      ``POST /admin/deploy``   {"dir": artifact_dir} — rolling weight
                               refresh across every live replica, one
                               at a time (synchronous; zero dropped
                               traffic)

    The router is itself a group member (host ``n_replicas``): it
    heartbeats, votes in control rounds and admits rejoining replicas
    — so even a 1-replica fleet has a survivor to re-admit a
    restarted replica, and a restarted ROUTER re-admits itself the
    same way (serving continues meanwhile: routing needs only the
    members snapshot, not membership)."""

    # completed/in-flight request tokens kept for idempotent replay
    # (a FleetClient that failed over back, or re-sent after a torn
    # response) — bounded so a long-lived router cannot grow forever
    TOKEN_CACHE = 4096

    def __init__(self, coord_address, n_replicas, port=0,
                 host="127.0.0.1", max_batch=8, batch_deadline_s=0.005,
                 max_queue=128, request_deadline_s=10.0,
                 poll_interval_s=0.05, ctl_interval_s=0.1,
                 hb_interval_s=0.25, timeout_s=30.0,
                 join_timeout_s=30.0, router_id=0, n_routers=1,
                 group_size=None, tenant_classes=None,
                 brownout_queue_depth=None, brownout_shed_rate=0.5,
                 qos_interval_s=0.1, qos_hysteresis=3):
        if not 0 <= int(router_id) < int(n_routers):
            raise ValueError("router_id %r out of range for %d "
                             "routers" % (router_id, n_routers))
        super(FleetRouter, self).__init__(
            coord_address, n_replicas,
            router_host_id(n_replicas, router_id),
            ctl_interval_s=ctl_interval_s, hb_interval_s=hb_interval_s,
            timeout_s=timeout_s, join_timeout_s=join_timeout_s,
            n_routers=n_routers, group_size=group_size)
        if int(max_batch) < 1:
            raise ValueError("max_batch must be >= 1")
        self.router_id = int(router_id)
        self._http_host = host
        self._http_port = int(port)
        self.max_batch = int(max_batch)
        self.batch_deadline_s = float(batch_deadline_s)
        self.max_queue = int(max_queue)
        self.request_deadline_s = float(request_deadline_s)
        self._poll_interval_s = float(poll_interval_s)
        self._queue = collections.deque()
        self._qcond = threading.Condition()
        # -- multi-tenant QoS (tentpole). No classes configured =
        # QoS OFF: every request takes the classic single-FIFO path
        # bit-for-bit; the per-tenant structures below stay empty.
        self._classes = parse_tenant_classes(tenant_classes)
        self._qos = bool(self._classes)
        self._class_default = self._classes.get(
            DEFAULT_TENANT, TenantClass(DEFAULT_TENANT))
        self._tenant_to_class = {}
        for c in self._classes.values():
            for t in c.tenants:
                self._tenant_to_class[t] = c
        # WFQ state, all under _qcond: per-tenant FIFO queues, the
        # start-time-fair-queuing virtual clock, and per-tenant
        # {finish tag, token bucket, inflight} scheduler state
        self._tqueues = {}
        self._tstate = {}
        self._vclock = 0.0
        # brownout (priority shed): the enacted verdict is a MINIMUM
        # admissible priority, escalated/relaxed only by the QoS
        # sampling thread on hysteresis streaks — admission reads the
        # frozen verdict, never the raw signals (the autoscaler's
        # frozen-signal discipline)
        self._bo_floor = None
        self._bo_levels = sorted(set(
            [c.priority for c in self._classes.values()]
            + [self._class_default.priority]))
        self._bo_hot = 0
        self._bo_cool = 0
        self._bo_prev = None
        self._brownout_queue_depth = (
            max(2, int(0.75 * int(max_queue)))
            if brownout_queue_depth is None
            else int(brownout_queue_depth))
        self._brownout_shed_rate = float(brownout_shed_rate)
        self._qos_interval_s = float(qos_interval_s)
        self._qos_hysteresis = int(qos_hysteresis)
        self._members_lock = threading.Lock()
        self._members = {}
        self._members_sig = None
        self._inflight = {}
        self._peer_inflight = {}
        self._peer_router_load = {}
        self._pick_seq = 0
        self._meta = None
        self._meta_lock = threading.Lock()
        self._deploy_lock = threading.Lock()
        # admission-leader state (term-stamped in the member registry)
        self._leader_lock = threading.Lock()
        self._is_leader = False
        self._leader_term = 0
        self._pub_sig = None
        # idempotent request replay: token -> _Pending (completed
        # entries keep their result until evicted)
        self._tokens = collections.OrderedDict()
        self._token_lock = threading.Lock()
        self._server = None
        self.address = None
        self.url = None

    # -- lifecycle ---------------------------------------------------------
    def _prepare(self):
        obs.set_service("router%d" % self.router_id, force=False)
        router = self
        import http.server

        class _Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, status, payload, raw=None):
                body = raw if raw is not None \
                    else json.dumps(payload).encode()
                self.send_response(status)
                ctype = "application/json" if raw is None else \
                    "text/plain; version=0.0.4; charset=utf-8"
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):   # noqa: N802 - stdlib naming
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    self._send(400, {"error": "malformed JSON body"})
                    return
                path = self.path.split("?", 1)[0]
                if path == "/infer":
                    self._send(*router._handle_infer(
                        body,
                        trace_header=self.headers.get("x-trace-id"),
                        headers={
                            "x-tenant":
                                self.headers.get("x-tenant"),
                            "x-deadline-ms":
                                self.headers.get("x-deadline-ms"),
                            "x-retry-budget":
                                self.headers.get("x-retry-budget")}))
                elif path == "/admin/deploy":
                    new_dir = body.get("dir")
                    if not new_dir:
                        self._send(400, {"error": "deploy needs "
                                         '{"dir": artifact_dir}'})
                        return
                    try:
                        timeout = float(
                            body.get("per_replica_timeout_s", 60.0))
                    except (TypeError, ValueError):
                        self._send(400, {"error":
                                         "per_replica_timeout_s must "
                                         "be a number"})
                        return
                    try:
                        summary = router.rolling_deploy(
                            new_dir, per_replica_timeout_s=timeout)
                        self._send(200, summary)
                    except FleetError as e:
                        self._send(500, {"error": str(e)})
                else:
                    self._send(404, {"error": "try /infer"})

            def do_GET(self):    # noqa: N802 - stdlib naming
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    text = resilience.metrics_text(
                        resilience.metrics(by_host=True))
                    self._send(200, None, raw=text.encode())
                elif path == "/healthz":
                    self._send(200, router.health())
                elif path == "/admin/trace":
                    self._send(200, obs.dump_dict())
                else:
                    self._send(404, {"error": "try /infer, /healthz "
                                     "or /metrics"})

            def log_message(self, *args):
                pass

        self._server, t = _start_http(
            _Handler, self._http_host, self._http_port,
            "paddle_tpu-fleet-router")
        self._threads.append(t)
        self.address = "%s:%d" % self._server.server_address[:2]
        self.url = "http://%s" % self.address
        bt = threading.Thread(target=self._batch_loop, daemon=True,
                              name="paddle_tpu-fleet-batcher")
        bt.start()
        self._threads.append(bt)
        if self._qos:
            qt = threading.Thread(target=self._qos_loop, daemon=True,
                                  name="paddle_tpu-fleet-qos")
            qt.start()
            self._threads.append(qt)

    def _after_join(self):
        pt = threading.Thread(target=self._members_loop, daemon=True,
                              name="paddle_tpu-fleet-members")
        pt.start()
        self._threads.append(pt)
        self._refresh_members()

    def _publish_info(self):
        """Advertise this router's blob: address (clients/tools
        discover the tier), its admission-leader claim (``lterm`` /
        ``leader`` — the term stamp a stale ex-leader is refused by),
        its per-replica in-flight counts (so sibling routers'
        least-loaded dispatch sees the REAL load, not just their own
        slice) and its queue/shed load signals (so the admission
        leader's autoscaler sees overload concentrated on a FOLLOWER
        — clients pin one endpoint, and in a multi-process tier the
        leader cannot read a sibling's process-local counters)."""
        with self._members_lock:
            inflight = {str(h): int(n)
                        for h, n in self._inflight.items() if n}
        with self._leader_lock:
            lterm, leader = self._leader_term, self._is_leader
        queue, shed, total = self._load_signals()
        try:
            self._co.put_info({"kind": "router", "addr": self.address,
                               "url": self.url,
                               "router_id": self.router_id,
                               "lterm": lterm, "leader": leader,
                               "inflight": inflight, "ready": False,
                               "queue": queue, "shed": shed,
                               "reqs": total,
                               "hq": self.high_priority_queue_depth()})
        except (CoordinationError, ConnectionError):
            return False
        return True

    def close(self):
        self._stop.set()
        with self._qcond:
            # requests still waiting to be coalesced will never be
            # dispatched: fail them NOW instead of letting each
            # caller block out its full request deadline
            stranded = list(self._queue)
            self._queue.clear()
            for q in self._tqueues.values():
                stranded.extend(q)
                q.clear()
            self._qcond.notify_all()
        self._fail(stranded, ServerOverloadedError(
            "router is closing — retry against its replacement"))
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        super(FleetRouter, self).close()

    # -- membership --------------------------------------------------------
    def _members_loop(self):
        while not self._stop.wait(self._poll_interval_s):
            self._refresh_members()

    def _refresh_members(self):
        try:
            m = self._co.members()
        except (CoordinationError, ConnectionError):
            return   # keep the last known table; the poll retries
        dl = m.get("hb_deadline_s")
        table, routers = {}, {}
        peer_inflight, peer_rload = {}, {}
        for h, info in m["info"].items():
            if not isinstance(info, dict) or h in m["lost"]:
                continue
            if info.get("kind") == "router":
                age = m["hb_age"].get(h)
                live = h == self._host_id or (
                    age is not None and (dl is None or age <= dl))
                if not live:
                    continue
                routers[h] = info
                if h != self._host_id:
                    for rid, n in (info.get("inflight") or {}).items():
                        try:
                            rid = int(rid)
                        except (TypeError, ValueError):
                            continue
                        peer_inflight[rid] = \
                            peer_inflight.get(rid, 0) + int(n)
                    peer_rload[h] = {
                        "queue": int(info.get("queue") or 0),
                        "shed": int(info.get("shed") or 0),
                        "reqs": int(info.get("reqs") or 0),
                        "hq": int(info.get("hq") or 0)}
                continue
            if info.get("kind") != "replica" \
                    or not info.get("ready") or not info.get("addr"):
                continue
            table[h] = {"addr": info["addr"],
                        "gen": info.get("gen"),
                        "dir": info.get("dir"),
                        "hb_age": m["hb_age"].get(h, 0.0)}
        # any artifact change in the table (a deploy step landing, a
        # direct per-replica /admin/refresh) invalidates the cached
        # export contract — batches must never be merged/split by a
        # stale factor map while replicas already serve a new artifact
        sig = tuple(sorted((h, v["gen"], v["dir"])
                           for h, v in table.items()))
        with self._members_lock:
            self._members = table
            self._peer_inflight = peer_inflight
            self._peer_router_load = peer_rload
        if sig != self._members_sig:
            self._members_sig = sig
            with self._meta_lock:
                self._meta = None
        self._update_leadership(m, routers)
        self._maybe_publish()

    def _update_leadership(self, m, routers):
        """Admission-leader election from the live member snapshot.
        Incumbency is STICKY: the live-looking router advertising the
        highest leader claim keeps the lease (a restarted ex-leader
        rejoins as a follower); only when no live claim exists does
        the lowest live router id take over, with a term bumped past
        every observed claim — the PR 9 term discipline."""
        with self._leader_lock:
            my_term, was_leader = self._leader_term, self._is_leader
        fenced = self._host_id in m["lost"]
        claims = []       # (lterm, router_host_id) of live claimants
        max_term = my_term
        for h, info in routers.items():
            term = int(info.get("lterm") or 0)
            if h == self._host_id:
                # the registry may lag our own state: use it live
                term, is_leader = my_term, was_leader and not fenced
            else:
                is_leader = bool(info.get("leader"))
            max_term = max(max_term, term)
            if is_leader:
                claims.append((term, h))
        # highest term wins; a same-term double claim (two routers that
        # raced the first election) breaks to the LOWEST router id
        incumbent = max(claims, key=lambda c: (c[0], -c[1])) \
            if claims else None
        if incumbent is not None and incumbent[0] >= max_term:
            leader_id = incumbent[1]
            new_term = incumbent[0]
        else:
            leader_id = min(routers) if routers else None
            new_term = max_term + 1     # takeover: fence every claim
        changed = False
        with self._leader_lock:
            if fenced or leader_id != self._host_id:
                if self._is_leader:
                    record_event("fleet_leader_demote",
                                 router=self._host_id, term=max_term)
                    changed = True
                self._is_leader = False
                if max_term > self._leader_term:
                    self._leader_term = max_term
                    changed = True
            elif not self._is_leader:
                self._is_leader = True
                self._leader_term = max(new_term, self._leader_term)
                record_event("fleet_leader_elect",
                             router=self._host_id,
                             term=self._leader_term)
                changed = True
            term_now = self._leader_term
        if changed:
            record_event("fleet_leader_term", router=self._host_id,
                         term=term_now)

    def _maybe_publish(self):
        """Republish the info blob only when it changed (leadership,
        the in-flight map, or the queue/shed load signals) — put_info
        is a sync-replicated op and must not run at poll rate for an
        IDLE router (an idle router's queue is 0 and its counters are
        static, so the signature holds still)."""
        with self._members_lock:
            inflight = tuple(sorted((h, int(n))
                             for h, n in self._inflight.items() if n))
        load = self._load_signals() + (
            self.high_priority_queue_depth(),)
        with self._leader_lock:
            sig = (self._is_leader, self._leader_term, inflight, load)
        # cache the signature only once the put LANDED: a publish
        # swallowed during a coordinator failover must be retried on
        # the next poll, or siblings read a stale leader claim and
        # stale in-flight counts until the state next changes
        if sig != self._pub_sig and self._publish_info():
            self._pub_sig = sig

    def is_leader(self):
        """Whether this router currently holds the admission lease."""
        with self._leader_lock:
            return self._is_leader

    @property
    def leader_term(self):
        with self._leader_lock:
            return self._leader_term

    def queue_depth(self):
        with self._qcond:
            return self._qdepth_locked()

    def _qdepth_locked(self):
        # exactly one of the two layouts holds requests: the single
        # FIFO (QoS off) or the per-tenant WFQ queues (QoS on)
        return len(self._queue) + sum(len(q)
                                      for q in self._tqueues.values())

    def high_priority_queue_depth(self):
        """Waiting requests belonging to the HIGHEST-priority class —
        the autoscaler's class-aware pressure signal: sustained
        high-class queueing grows the fleet even while total depth
        looks tame (the brownout already shed the rest). 0 when QoS
        is off."""
        if not self._qos:
            return 0
        hi = self._bo_levels[-1]
        with self._qcond:
            return sum(len(q) for t, q in self._tqueues.items()
                       if self._class_of(t).priority >= hi)

    def _load_signals(self):
        """``(queue_depth, shed_total, requests_total)`` for THIS
        router — its process-local slice of the fleet-wide autoscale
        signal (shared with siblings through the info blob)."""
        totals = resilience.router_totals(by_router=True).get(
            str(self._host_id), None)
        reqs = (totals or {"requests": {}})["requests"]
        return (self.queue_depth(), int(reqs.get("shed", 0)),
                int(sum(reqs.values())))

    def peer_router_load(self):
        """{router_host_id: {"queue", "shed", "reqs"}} last read from
        each live SIBLING router's info blob."""
        with self._members_lock:
            return {h: dict(v)
                    for h, v in self._peer_router_load.items()}

    def _admission_enactor(self):
        """Only the admission leader enacts — and even the leader
        re-checks the member registry AT ENACT TIME: a higher term
        stamped by any router means we are the stale ex-leader the
        term fence exists for, and the enactment is refused."""
        with self._leader_lock:
            if not self._is_leader:
                return False
            term = self._leader_term
        try:
            m = self._co.members()
        except (CoordinationError, ConnectionError):
            return False
        if self._host_id in m["lost"]:
            return False
        for h, info in m["info"].items():
            if h != self._host_id and isinstance(info, dict) \
                    and info.get("kind") == "router" \
                    and int(info.get("lterm") or 0) > term:
                with self._leader_lock:
                    self._is_leader = False
                    self._leader_term = max(self._leader_term,
                                            int(info["lterm"]))
                record_event("fleet_leader_stale",
                             router=self._host_id,
                             term=int(info["lterm"]))
                return False
        return True

    def routable(self):
        """{replica_id: {"addr", "gen", "dir", "hb_age"}} of every
        replica the router would currently dispatch to."""
        with self._members_lock:
            return {h: dict(v) for h, v in self._members.items()}

    def health(self):
        with self._qcond:
            depth = self._qdepth_locked()
            tenant_depth = {t: len(q)
                            for t, q in self._tqueues.items() if q}
            bo_floor = self._bo_floor
        with self._members_lock:
            inflight = dict(self._inflight)
        with self._leader_lock:
            leader, lterm = self._is_leader, self._leader_term
        out = {"live": True, "replicas": self.routable(),
               "queue_depth": depth, "inflight": inflight,
               "n_replicas": self.n_replicas,
               "router_id": self.router_id,
               "n_routers": self.n_routers,
               "group_size": self.group_size,
               "leader": leader, "leader_term": lterm,
               "max_batch": self.max_batch,
               "batch_deadline_s": self.batch_deadline_s}
        if self._qos:
            out["qos"] = {
                "classes": sorted(self._classes),
                "tenant_queue_depth": tenant_depth,
                "brownout_floor": bo_floor,
                "high_priority_queue_depth":
                    self.high_priority_queue_depth()}
        return out

    def _pick_replica(self, tried):
        """Least-loaded live replica not yet tried for this batch:
        fewest FLEET-WIDE router-dispatched batches in flight (own
        counts plus every sibling router's, shared through the member
        registry's info blobs — a failed-over request must not
        double-count a replica's load); equally-loaded replicas rotate
        round-robin. (NOT heartbeat freshness: the lease cadences of
        healthy replicas phase-lock against the members poll, and a
        fixed freshness tie-break then shadows one replica completely
        — it never takes traffic and its buckets go cold.)"""
        with self._members_lock:
            peers = self._peer_inflight
            cands = sorted((self._inflight.get(h, 0)
                            + peers.get(h, 0), h, v["addr"])
                           for h, v in self._members.items()
                           if h not in tried)
            if not cands:
                return None
            least = [c for c in cands if c[0] == cands[0][0]]
            self._pick_seq += 1
            _, h, addr = least[self._pick_seq % len(least)]
        return h, addr

    def _inc_inflight(self, rid, d):
        with self._members_lock:
            n = self._inflight.get(rid, 0) + d
            self._inflight[rid] = max(0, n)
            # the gauge write stays under the lock: published outside
            # it, a racing +1/-1 pair can land out of order and strand
            # the exported series at a stale nonzero value
            resilience.set_router_inflight(
                rid, self._inflight[rid], router=self._host_id)

    # -- the export contract (what batching splits by) ---------------------
    def _get_meta(self):
        with self._meta_lock:
            if self._meta is not None:
                return self._meta
        for rid, ent in sorted(self.routable().items()):
            try:
                status, resp = http_json(
                    "GET", "http://%s/meta" % ent["addr"],
                    timeout_s=5.0)
            except (OSError, ValueError):
                continue
            if status == 200 and "feed_names" in resp:
                with self._meta_lock:
                    self._meta = resp
                return resp
        return None

    def _request_rows(self, feeds, meta):
        """The request batch implied by its dynamic feeds' row counts
        — the export's recorded factors, exactly the ServingPredictor
        bucket math (dim0 = factor * batch) — plus a DEEP shape check
        against the export's fixed dims. Validation lives here, at
        admission: a malformed request (ragged rows, wrong width, a
        missing feed) coalesced into a micro-batch would otherwise
        fail on the replica and take every innocent sibling in the
        batch down with it."""
        n = None
        if meta["dynamic_batch"]:
            for name, f in meta["feed_batch_factors"].items():
                if not f:
                    continue
                if name not in feeds:
                    raise ValueError("request is missing feed %r"
                                     % name)
                rows = len(feeds[name])
                if rows % f:
                    raise ValueError(
                        "feed %r has %d rows, not a multiple of its "
                        "batch factor %d" % (name, rows, f))
                got = rows // f
                if n is None:
                    n = got
                elif got != n:
                    raise ValueError(
                        "batch-dynamic feeds disagree on the batch: "
                        "feed %r implies %d, earlier feeds %d"
                        % (name, got, n))
        n = 1 if n is None else n
        inner = meta.get("feed_inner_shapes")
        if inner:
            import numpy as np
            factors = meta["feed_batch_factors"]
            for name in meta["feed_names"]:
                if name not in feeds:
                    raise ValueError("request is missing feed %r"
                                     % name)
                f = factors.get(name, 0)
                want = ([n * f] + list(inner[name])) if f \
                    else list(inner[name])
                try:
                    arr = np.asarray(feeds[name])
                except Exception:   # ragged nesting raises in numpy
                    raise ValueError(
                        "feed %r is ragged/malformed, expected shape "
                        "%s" % (name, want))
                if arr.dtype == object or list(arr.shape) != want:
                    raise ValueError(
                        "feed %r has shape %s, expected %s"
                        % (name, list(arr.shape), want))
        return n

    # -- request intake ----------------------------------------------------
    def _finish_pending(self, p, deadline, outcome_replayed=False):
        """Wait out one pending request and account its terminal
        outcome (``replay`` for a token replay riding the original —
        the caller's view stays one request, the counters stay
        honest). Non-replay completions additionally feed the top-K
        slow-request exemplars (latency + trace id) that
        ``router_totals()`` exports — the bridge from a fat p99 to
        the exact timeline behind it."""
        if not p.event.wait(max(0.0, deadline - time.monotonic())
                            + 0.05):
            p.abandoned = True
            resilience.record_router_request("deadline",
                                             router=self._host_id,
                                             tenant=p.tenant)
            if not outcome_replayed:
                # a token replay waiting out the same _Pending must
                # not double-spend a top-K exemplar slot on one
                # logical request
                resilience.record_router_slow(
                    time.monotonic() - p.enqueued, trace=p.trace,
                    router=self._host_id, tenant=p.tenant)
            raise DeadlineExceededError(
                "request did not complete within its deadline")
        if not outcome_replayed:
            resilience.record_router_slow(
                time.monotonic() - p.enqueued, trace=p.trace,
                router=self._host_id, tenant=p.tenant)
        if p.error is not None:
            resilience.record_router_request(
                "shed" if isinstance(p.error, ServerOverloadedError)
                else "deadline"
                if isinstance(p.error, DeadlineExceededError)
                else "error", router=self._host_id, tenant=p.tenant)
            raise p.error
        resilience.record_router_request(
            "replay" if outcome_replayed else "ok",
            router=self._host_id, tenant=p.tenant)
        return p.result

    def _remember_token(self, token, p):
        with self._token_lock:
            self._tokens[token] = p
            while len(self._tokens) > self.TOKEN_CACHE:
                self._tokens.popitem(last=False)

    def submit(self, feeds, deadline_s=None, token=None, trace=None,
               tenant=None, deadline_budget_ms=None,
               retry_budget=None):
        """Route one request (dict name -> rows as nested lists).
        Returns ``{"outputs", "dtypes", "replica", "generation"}``.
        ``token`` (an opaque client string) makes the request
        IDEMPOTENT on this router: a replay with the same token rides
        the original in-flight request (or returns its cached result)
        instead of enqueueing a duplicate — what lets a FleetClient
        re-send blindly after a torn response or a failover loop back.
        ``trace`` is the propagated ``(trace_id, parent_span_id)``
        context from the caller's ``x-trace-id`` header — the request
        gets a ``router.serve`` span (with queue/dispatch children)
        under the caller's trace, so one client request is one
        timeline across processes.
        ``tenant`` is the request's QoS identity (the ``x-tenant``
        header / ``"tenant"`` body field; absent = ``"default"``):
        with tenant classes configured it selects the class whose
        weight/quota/priority govern admission and queueing, and it
        labels every counter, exemplar and span either way.
        ``deadline_budget_ms`` is the REMAINING cross-hop deadline
        budget (the ``x-deadline-ms`` header): it caps ``deadline_s``,
        an already-spent budget is refused 504-style WITHOUT queueing,
        and whatever is left at dispatch time rides the next hop's
        ``x-deadline-ms``. ``retry_budget`` (``x-retry-budget``) caps
        how many replica attempts this request may burn across
        retry-on-sibling.
        Raises ServerOverloadedError (queue full / quota or brownout
        shed / every replica shedding), DeadlineExceededError,
        ValueError (malformed request) or RuntimeError (upstream
        failure after retries)."""
        tr, parent = trace if trace else (None, None)
        tenant = tenant or DEFAULT_TENANT
        with obs.span("router.serve", trace_id=tr, parent=parent,
                      router=self._host_id, tenant=tenant) as sp:
            return self._submit_traced(feeds, deadline_s, token, sp,
                                       tenant, deadline_budget_ms,
                                       retry_budget)

    def _submit_traced(self, feeds, deadline_s, token, sp, tenant,
                       deadline_budget_ms, retry_budget):
        if deadline_budget_ms is not None:
            budget_s = float(deadline_budget_ms) / 1000.0
            if budget_s <= 0:
                # the budget died upstream (a slow client hop, a
                # queueing router ahead of us): refuse WITHOUT
                # queueing — dispatching would burn replica time on
                # an answer nobody is waiting for
                resilience.record_router_expired(
                    "queue", tenant=tenant, router=self._host_id)
                resilience.record_router_request(
                    "deadline", router=self._host_id, tenant=tenant)
                raise DeadlineExceededError(
                    "deadline budget exhausted before admission — "
                    "refused without queueing")
            deadline_s = budget_s if deadline_s is None \
                else min(float(deadline_s), budget_s)
        deadline = time.monotonic() + (
            self.request_deadline_s if deadline_s is None
            else float(deadline_s))
        if token:
            with self._token_lock:
                prev = self._tokens.get(token)
            # a replay rides only an IN-FLIGHT or SUCCEEDED original.
            # A failed/abandoned one must NOT answer from the cache:
            # the client retrying a shed against a single-router
            # endpoint list would be replayed its own stale failure
            # forever — the retry re-enqueues fresh (last write wins
            # in the token cache)
            if prev is not None and prev.error is None \
                    and not prev.abandoned:
                return self._finish_pending(prev, deadline,
                                            outcome_replayed=True)
        meta = self._get_meta()
        if meta is None:
            resilience.record_router_request("error",
                                             router=self._host_id,
                                             tenant=tenant)
            raise FleetError("no live replica to learn the export "
                             "contract from — is the fleet up?")
        try:
            n = self._request_rows(feeds, meta)
            if meta["dynamic_batch"] and meta.get("max_bucket") \
                    and n > int(meta["max_bucket"]):
                # reject at ADMISSION: dispatched, this request would
                # 500 deterministically on every replica — burning a
                # retry per sibling to turn a client error into a 502
                raise ValueError(
                    "request batch %d exceeds the largest exported "
                    "bucket %d — re-export with a larger batch_sizes "
                    "entry" % (n, int(meta["max_bucket"])))
        except ValueError:
            resilience.record_router_request("error",
                                             router=self._host_id,
                                             tenant=tenant)
            raise
        p = _Pending(feeds, n, deadline, tenant=tenant,
                     retry_budget=retry_budget)
        if sp.trace is not None:
            p.trace, p.span, p.t_enq = sp.trace, sp.id, obs.now()
        with self._qcond:
            if self._qos:
                msg = self._qos_admit_locked(p, time.monotonic())
                if msg is not None:
                    resilience.record_router_request(
                        "shed", router=self._host_id, tenant=tenant)
                    raise ServerOverloadedError(msg)
            else:
                if len(self._queue) >= self.max_queue:
                    resilience.record_router_request(
                        "shed", router=self._host_id, tenant=tenant)
                    raise ServerOverloadedError(
                        "router queue is full (%d waiting) — "
                        "shedding load; retry with backoff"
                        % self.max_queue)
                self._queue.append(p)
                resilience.set_router_queue_depth(
                    len(self._queue), router=self._host_id)
            self._qcond.notify_all()
        if token:
            self._remember_token(token, p)
        if not self._qos:
            return self._finish_pending(p, deadline)
        try:
            return self._finish_pending(p, deadline)
        finally:
            # the in-flight quota covers admission -> completion
            # (queued OR dispatched), whatever path ended it
            with self._qcond:
                self._tstate_for(p.tenant)["inflight"] -= 1

    def _handle_infer(self, body, trace_header=None, headers=None):
        headers = headers or {}
        feeds = body.get("feeds")
        if not isinstance(feeds, dict):
            return 400, {"error": 'infer needs {"feeds": {name: rows}}'}
        deadline_s = body.get("deadline_s")
        if deadline_s is not None:
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError):
                return 400, {"error": "deadline_s must be a number, "
                             "got %r" % (deadline_s,)}
        token = body.get("token")
        if token is not None and not isinstance(token, str):
            return 400, {"error": "token must be a string"}
        tenant = headers.get("x-tenant") or body.get("tenant")
        if tenant is not None and not isinstance(tenant, str):
            return 400, {"error": "tenant must be a string"}
        deadline_budget_ms = headers.get("x-deadline-ms")
        if deadline_budget_ms is not None:
            try:
                deadline_budget_ms = float(deadline_budget_ms)
            except (TypeError, ValueError):
                return 400, {"error": "x-deadline-ms must be a "
                             "number, got %r" % (deadline_budget_ms,)}
        retry_budget = headers.get("x-retry-budget")
        if retry_budget is not None:
            try:
                retry_budget = int(retry_budget)
            except (TypeError, ValueError):
                return 400, {"error": "x-retry-budget must be an "
                             "integer, got %r" % (retry_budget,)}
            if retry_budget < 1:
                return 400, {"error": "x-retry-budget must be >= 1"}
        try:
            return 200, self.submit(
                feeds, deadline_s=deadline_s, token=token,
                trace=obs.parse_header(trace_header), tenant=tenant,
                deadline_budget_ms=deadline_budget_ms,
                retry_budget=retry_budget)
        except ServerOverloadedError as e:
            return 503, {"error": str(e), "kind": "overloaded"}
        except DeadlineExceededError as e:
            return 504, {"error": str(e), "kind": "deadline"}
        except ValueError as e:
            return 400, {"error": str(e)}
        except (FleetError, RuntimeError, OSError) as e:
            # OSError covers the ConnectionError a batch fails with
            # when EVERY live replica was unreachable — the caller
            # must see a status code, never an aborted connection
            return 502, {"error": str(e), "kind": "upstream"}

    # -- multi-tenant QoS --------------------------------------------------
    def _class_of(self, tenant):
        """Resolve a tenant to its :class:`TenantClass`: an explicit
        ``tenants`` membership wins, then a class NAMED like the
        tenant, then the ``default`` class (implicit weight-1
        priority-0 unless configured)."""
        c = self._tenant_to_class.get(tenant)
        if c is not None:
            return c
        return self._classes.get(tenant, self._class_default)

    def _tstate_for(self, tenant):
        """Per-tenant mutable QoS state (caller holds ``_qcond``):
        token-bucket level, in-flight count and the SFQ finish tag of
        the tenant's last admitted request."""
        st = self._tstate.get(tenant)
        if st is None:
            c = self._class_of(tenant)
            st = self._tstate[tenant] = {
                "tokens": c.burst if c.burst is not None else 0.0,
                "t_tok": time.monotonic(),
                "inflight": 0, "finish": 0.0}
        return st

    def _qos_admit_locked(self, p, now):
        """Classed admission (caller holds ``_qcond``): brownout
        floor, global queue cap, token-bucket rate, in-flight quota —
        in that order, so a browned-out class cannot drain tokens it
        would not get to spend. Returns the shed reason (``None`` =
        admitted: the request is tagged with its SFQ virtual times
        and appended to its tenant's queue)."""
        c = self._class_of(p.tenant)
        if self._bo_floor is not None and c.priority < self._bo_floor:
            return ("brownout shed: class %r (priority %d) is below "
                    "the current floor %d — the router keeps only "
                    "its highest classes under overload; retry with "
                    "backoff" % (c.name, c.priority, self._bo_floor))
        if self._qdepth_locked() >= self.max_queue:
            return ("router queue is full (%d waiting) — shedding "
                    "load; retry with backoff" % self.max_queue)
        st = self._tstate_for(p.tenant)
        if c.rate is not None:
            st["tokens"] = min(c.burst, st["tokens"]
                               + (now - st["t_tok"]) * c.rate)
            st["t_tok"] = now
            if st["tokens"] < 1.0:
                return ("tenant %r is over class %r's rate quota "
                        "(%g req/s) — shedding; retry with backoff"
                        % (p.tenant, c.name, c.rate))
            st["tokens"] -= 1.0
        if c.max_inflight is not None \
                and st["inflight"] >= c.max_inflight:
            return ("tenant %r is at class %r's in-flight quota (%d) "
                    "— shedding; retry with backoff"
                    % (p.tenant, c.name, c.max_inflight))
        st["inflight"] += 1
        p.vstart = max(self._vclock, st["finish"])
        p.vfinish = p.vstart + p.n / c.weight
        st["finish"] = p.vfinish
        q = self._tqueues.get(p.tenant)
        if q is None:
            q = self._tqueues[p.tenant] = collections.deque()
        q.append(p)
        resilience.set_router_tenant_queue_depth(
            p.tenant, len(q), router=self._host_id)
        resilience.set_router_queue_depth(self._qdepth_locked(),
                                          router=self._host_id)
        return None

    def _qos_loop(self):
        while not self._stop.wait(self._qos_interval_s):
            self._qos_tick()

    def _qos_tick(self):
        """Brownout controller tick — the autoscaler's frozen-signal
        discipline applied to shedding: sample queue depth and the
        shed-rate delta, and only a ``qos_hysteresis``-long streak of
        hot (cool) samples moves the admissible-priority floor one
        class level up (down). Admission reads the FROZEN verdict
        (``_bo_floor``) — per-request heuristics would flap at
        request rate. The floor never exceeds the highest configured
        priority, so the highest class is never browned out."""
        depth = self.queue_depth()
        _, shed, total = self._load_signals()
        prev = self._bo_prev
        self._bo_prev = (shed, total)
        if prev is None:
            return
        d_shed, d_total = shed - prev[0], total - prev[1]
        rate = float(d_shed) / d_total if d_total > 0 else 0.0
        hot = depth >= self._brownout_queue_depth \
            or rate >= self._brownout_shed_rate
        with self._qcond:
            levels, cur = self._bo_levels, self._bo_floor
            nxt = cur
            if hot:
                self._bo_hot += 1
                self._bo_cool = 0
                if self._bo_hot >= self._qos_hysteresis:
                    above = [lv for lv in levels
                             if cur is None or lv > cur]
                    # the top level stays admissible: the floor may
                    # reach levels[-1] (only the highest class kept),
                    # never pass it
                    if len(above) > (1 if cur is None else 0):
                        nxt = above[1] if cur is None else above[0]
            else:
                self._bo_cool += 1
                self._bo_hot = 0
                if self._bo_cool >= self._qos_hysteresis \
                        and cur is not None:
                    idx = levels.index(cur)
                    nxt = levels[idx - 1] if idx > 1 else None
            if nxt != cur:
                self._bo_floor = nxt
                self._bo_hot = self._bo_cool = 0
        if nxt != cur:
            record_event("router_brownout", router=self._host_id,
                         floor=nxt, queue=depth,
                         shed_rate=round(rate, 3))

    # -- continuous micro-batching -----------------------------------------
    def _batch_loop(self):
        cut = self._cut_batch_wfq if self._qos else self._cut_batch
        while not self._stop.is_set():
            batch = cut()
            if batch:
                resilience.observe_router_batch(len(batch),
                                                router=self._host_id)
                t = threading.Thread(target=self._dispatch,
                                     args=(batch,), daemon=True,
                                     name="paddle_tpu-fleet-dispatch")
                t.start()

    def _cut_batch(self):
        """Block until a batch is due, then cut it: requests coalesce
        in arrival order while their summed request-batch stays within
        ``max_batch``; the cut happens the moment the cap is reached
        or the OLDEST waiting request has aged ``batch_deadline_s``.
        Expired/abandoned requests are dropped here (their callers
        already took the deadline path)."""
        while not self._stop.is_set():
            # meta resolution happens OUTSIDE _qcond: a cold cache is
            # an HTTP GET /meta (5s timeout per replica), and holding
            # the condition through it would stall every submit(),
            # shed and health() exactly when the fleet is degraded
            meta = self._get_meta()
            if meta is None:
                self._stop.wait(0.05)
                continue
            coalescing = bool(meta["dynamic_batch"])
            # the coalescing cap must respect the EXPORT: a merged
            # batch larger than the biggest exported bucket would be
            # a deterministic ValueError on every replica — a
            # fleet-wide failure that only appears under load
            cap = self.max_batch
            if coalescing and meta.get("max_bucket"):
                cap = min(cap, int(meta["max_bucket"]))
            # static (factor-0) feeds are shipped ONCE per merged
            # batch, so requests may only share a batch when their
            # static tensors are EQUAL — silently computing B's
            # outputs from A's static feed would be wrong data, not
            # even an error
            static_names = [nm for nm, f
                            in meta["feed_batch_factors"].items()
                            if not f]
            with self._qcond:
                now = time.monotonic()
                while self._queue and (self._queue[0].abandoned
                                       or now > self._queue[0].deadline):
                    self._drop_expired_locked(self._queue.popleft(),
                                              now)
                if not self._queue:
                    resilience.set_router_queue_depth(
                        0, router=self._host_id)
                    self._qcond.wait(0.05)
                    continue
                first = self._queue[0]
                rows = 0
                for p in self._queue:
                    if p.abandoned or now > p.deadline:
                        continue
                    rows += p.n
                cut_at = first.enqueued + self.batch_deadline_s
                if coalescing and rows < cap and now < cut_at:
                    self._qcond.wait(min(cut_at - now, 0.05))
                    continue
                batch, rows = [], 0
                while self._queue:
                    p = self._queue[0]
                    if p.abandoned or now > p.deadline:
                        self._drop_expired_locked(
                            self._queue.popleft(), now)
                        continue
                    if batch and (not coalescing
                                  or rows + p.n > cap
                                  or any(p.feeds.get(nm)
                                         != batch[0].feeds.get(nm)
                                         for nm in static_names)):
                        break
                    self._queue.popleft()
                    batch.append(p)
                    rows += p.n
                resilience.set_router_queue_depth(len(self._queue),
                                                  router=self._host_id)
                if batch and obs.enabled():
                    self._record_cut_spans(batch)
                return batch
        return []

    def _drop_expired_locked(self, p, now):
        """Account one request dropped from a queue without ever
        being dispatched. ``where="queue"`` on the deadline-expired
        counter is the propagated-budget discipline in action: the
        budget died while the request waited, so no replica slot is
        burnt on it (the caller already took the deadline path)."""
        if now > p.deadline:
            resilience.record_router_expired(
                "queue", tenant=p.tenant, router=self._host_id)

    def _record_cut_spans(self, batch):
        # retroactive per-request queue spans (enqueue -> cut) + one
        # coalesce span on the oldest member: "was the latency queue
        # wait or replica time" is answerable per request
        t_cut = obs.now()
        lead = next((p for p in batch if p.trace is not None), None)
        if lead is not None:
            obs.record("router.coalesce", lead.t_enq, t_cut,
                       trace_id=lead.trace, parent=lead.span,
                       batch=len(batch))
        for p in batch:
            if p.trace is not None:
                obs.record("router.queue", p.t_enq, t_cut,
                           trace_id=p.trace, parent=p.span,
                           tenant=p.tenant)

    def _cut_batch_wfq(self):
        """The QoS cutter: like :meth:`_cut_batch`, but requests wait
        in PER-TENANT queues and the cut drains them by start-time
        fair queueing — each queue head carries a virtual finish tag
        stamped at admission (``vstart = max(vclock, tenant's last
        finish)``, ``vfinish = vstart + rows / weight``) and the
        cutter repeatedly picks the smallest ``vfinish`` among heads,
        advancing the virtual clock to the pick's ``vstart``. Over
        any busy interval each tenant's served rows converge to its
        weight share, an idle tenant builds no credit (its next
        vstart jumps to the live vclock), and a flooding tenant only
        queues behind its own backlog — the isolation the single
        FIFO cannot give."""
        while not self._stop.is_set():
            meta = self._get_meta()
            if meta is None:
                self._stop.wait(0.05)
                continue
            coalescing = bool(meta["dynamic_batch"])
            cap = self.max_batch
            if coalescing and meta.get("max_bucket"):
                cap = min(cap, int(meta["max_bucket"]))
            static_names = [nm for nm, f
                            in meta["feed_batch_factors"].items()
                            if not f]
            with self._qcond:
                now = time.monotonic()
                for t, q in self._tqueues.items():
                    while q and (q[0].abandoned
                                 or now > q[0].deadline):
                        self._drop_expired_locked(q.popleft(), now)
                heads = [q[0] for q in self._tqueues.values() if q]
                if not heads:
                    resilience.set_router_queue_depth(
                        0, router=self._host_id)
                    self._qcond.wait(0.05)
                    continue
                rows = sum(p.n for q in self._tqueues.values()
                           for p in q
                           if not (p.abandoned or now > p.deadline))
                cut_at = min(p.enqueued for p in heads) \
                    + self.batch_deadline_s
                if coalescing and rows < cap and now < cut_at:
                    self._qcond.wait(min(cut_at - now, 0.05))
                    continue
                batch, rows = [], 0
                while True:
                    head = None
                    for q in self._tqueues.values():
                        if q and (head is None
                                  or q[0].vfinish < head[0].vfinish):
                            head = q
                    if head is None:
                        break
                    p = head[0]
                    if p.abandoned or now > p.deadline:
                        self._drop_expired_locked(head.popleft(), now)
                        continue
                    if batch and (not coalescing
                                  or rows + p.n > cap
                                  or any(p.feeds.get(nm)
                                         != batch[0].feeds.get(nm)
                                         for nm in static_names)):
                        break
                    head.popleft()
                    self._vclock = max(self._vclock, p.vstart)
                    batch.append(p)
                    rows += p.n
                for t, q in self._tqueues.items():
                    resilience.set_router_tenant_queue_depth(
                        t, len(q), router=self._host_id)
                resilience.set_router_queue_depth(
                    self._qdepth_locked(), router=self._host_id)
                if not batch:
                    continue   # everything waiting had expired
                if obs.enabled():
                    self._record_cut_spans(batch)
                return batch
        return []

    @staticmethod
    def _merge(batch, meta):
        merged = {}
        for name in meta["feed_names"]:
            merged[name] = []
            for p in batch:
                merged[name].extend(p.feeds.get(name, []))
        # a static feed (factor 0) must not be concatenated: every
        # request carries the same full tensor — ship the first
        for name, f in meta["feed_batch_factors"].items():
            if not f and batch:
                merged[name] = batch[0].feeds.get(name, [])
        return merged

    def _dispatch(self, batch):
        """Send one coalesced batch to the least-loaded live replica,
        retrying on an untried sibling while the deadlines allow — a
        replica death mid-flight costs a retry, not a failure. The
        dispatch budget is the batch's MINIMUM remaining deadline, so
        when a short-deadline member expires it is failed ALONE and
        the survivors are re-merged and retried on their own budget —
        one impatient caller must not poison its coalesced siblings."""
        meta = self._get_meta()
        if meta is None:
            self._fail(batch, FleetError("no live replica"))
            return
        tried = set()
        last_err = None
        merged = None
        attempt = 0
        # retry-on-sibling is bounded by the STRICTEST member budget
        # (x-retry-budget): a replica outage under load must cost a
        # bounded number of attempts per request, not a retry storm
        retry_budget = None
        for p in batch:
            if p.retry_budget is not None:
                retry_budget = p.retry_budget if retry_budget is None \
                    else min(retry_budget, p.retry_budget)
        n_attempts = 0
        while True:
            now = time.monotonic()
            expired = [p for p in batch if now > p.deadline]
            if expired:
                for p in expired:
                    # cut but never answered: the budget died between
                    # the cut and a successful dispatch
                    resilience.record_router_expired(
                        "dispatch", tenant=p.tenant,
                        router=self._host_id)
                self._fail(expired,
                           last_err or DeadlineExceededError(
                               "request deadline expired before any "
                               "replica answered"))
                batch = [p for p in batch if now <= p.deadline]
                # the recomposed batch is a NEW dispatch: earlier
                # failures belonged to the old composition (a replica
                # that 504'd the impatient member's budget can serve
                # the survivors' own), so the replica set reopens
                merged = None
                tried = set()
                last_err = None
            if not batch:
                return
            if retry_budget is not None and n_attempts >= retry_budget:
                self._fail(batch, last_err or ServerOverloadedError(
                    "retry budget (%d attempts) exhausted"
                    % retry_budget))
                return
            if merged is None:
                merged = self._merge(batch, meta)
            remaining = min(p.deadline for p in batch) - now
            if remaining <= 0:
                continue             # the loop top expires them
            target = self._pick_replica(tried)
            if target is None:
                self._fail(batch, last_err or ServerOverloadedError(
                    "no live replica to dispatch to"))
                return
            rid, addr = target
            payload = {"feeds": merged, "deadline_s": remaining}
            # the remaining budget rides the next hop as x-deadline-ms
            # (RE-COMPUTED per attempt — each retry ships a smaller
            # budget), so the replica can refuse already-expired work
            # before burning a batch slot on it. x-tenant carries the
            # LEAD member's identity (a coalesced batch may mix
            # tenants; per-request identity lives router-side)
            headers = {"x-deadline-ms": "%d" % int(remaining * 1000.0),
                       "x-tenant": batch[0].tenant}
            n_attempts += 1
            # propagate the (lead) trace context to the replica so its
            # serve span joins the same timeline; the per-attempt
            # dispatch spans below are recorded per coalesced request,
            # tagged replica + outcome — a retry-on-sibling is two
            # dispatch spans under one router.serve parent
            traced = obs.enabled()
            if traced:
                attempt += 1
                t_att = obs.now()
                lead = next((p for p in batch
                             if p.trace is not None), None)
                if lead is not None:
                    headers["x-trace-id"] = \
                        "%s:%s" % (lead.trace, lead.span)
            self._inc_inflight(rid, +1)
            try:
                # inside the try on purpose: an injected OSError takes
                # the exact retry-on-sibling path a dead replica does
                # (``host`` filter = target replica id)
                faultinject.hit("serving.dispatch", host=rid)
                status, resp = http_json(
                    "POST", "http://%s/infer" % addr, payload,
                    timeout_s=remaining + 0.5, headers=headers)
            except (OSError, ValueError) as e:
                # a SIGKILLed replica mid-flight lands here: the
                # connection resets, the batch retries on a sibling.
                # Connection-level failures are RARE (a death, not
                # load) — they warrant an event as well as the counter
                last_err = ConnectionError(
                    "replica %d unreachable: %s" % (rid, e))
                tried.add(rid)
                resilience.record_router_retry(rid,
                                               router=self._host_id)
                record_event("router_retry", replica=rid,
                             error=type(e).__name__)
                if traced:
                    self._record_dispatch(batch, t_att, rid,
                                          "unreachable", attempt)
                continue
            finally:
                self._inc_inflight(rid, -1)
            if status == 200:
                if traced:
                    self._record_dispatch(batch, t_att, rid, "ok",
                                          attempt)
                self._split(batch, resp, meta)
                return
            tried.add(rid)
            if status == 503:
                outcome = "shed"
                last_err = ServerOverloadedError(
                    resp.get("error", "replica %d is shedding" % rid))
            elif status == 504:
                outcome = "deadline"
                last_err = DeadlineExceededError(
                    resp.get("error", "replica %d deadline" % rid))
            else:
                outcome = "error"
                last_err = RuntimeError(
                    resp.get("error",
                             "replica %d answered HTTP %d"
                             % (rid, status)))
            if traced:
                self._record_dispatch(batch, t_att, rid, outcome,
                                      attempt)
            # 5xx retries are LOAD-driven (a shed storm emits one per
            # tried replica per batch, at request rate): counter only,
            # never the bounded event log
            resilience.record_router_retry(rid, router=self._host_id)

    @staticmethod
    def _record_dispatch(batch, t0, rid, outcome, attempt):
        """One finished dispatch-attempt span per coalesced traced
        request, parented under its router.serve span."""
        t1 = obs.now()
        for p in batch:
            if p.trace is not None:
                obs.record("router.dispatch", t0, t1,
                           trace_id=p.trace, parent=p.span,
                           replica=rid, outcome=outcome,
                           attempt=attempt, tenant=p.tenant)

    @staticmethod
    def _fail(batch, err):
        for p in batch:
            p.error = err
            # terminal: a token replay answers from result/error only,
            # so drop the payload instead of pinning it in the token
            # cache until 4096 newer requests evict it
            p.feeds = None
            p.event.set()

    def _split(self, batch, resp, meta):
        """Give each coalesced request its own slice of the batched
        outputs, by the EXPORT's fetch factors (factor 0 = static
        output, replicated to every caller)."""
        outs = resp.get("outputs", [])
        dtypes = resp.get("dtypes", [])
        factors = [meta["fetch_batch_factors"].get(name, 0)
                   for name in meta["fetch_names"]]
        off = 0
        for p in batch:
            mine = []
            for o, f in zip(outs, factors):
                if f and isinstance(o, list):
                    mine.append(o[off * f:(off + p.n) * f])
                else:
                    mine.append(o)
            p.result = {"outputs": mine, "dtypes": dtypes,
                        "replica": resp.get("replica"),
                        "generation": resp.get("generation")}
            p.error = None
            # terminal: replay reads result only — don't pin the
            # request payload in the token cache
            p.feeds = None
            p.event.set()
            off += p.n

    # -- rolling weight refresh --------------------------------------------
    def rolling_deploy(self, artifact_dir, per_replica_timeout_s=60.0):
        """Refresh every live replica's weights to ``artifact_dir``,
        ONE replica at a time: ask it to refresh (it self-fences,
        reloads + warms, rejoins), wait until it is back in rotation
        on the new artifact, then move to the next — traffic keeps
        flowing to the rest throughout, so a deploy drops nothing.
        Returns ``{"refreshed": [ids], "dir": dir}``; raises
        :class:`FleetError` when a replica does not come back in
        time (the deploy stops there — the fleet keeps serving on the
        replicas already refreshed plus the untouched tail), or when
        another deploy is already in progress (two interleaved
        deploys would fence more than one replica at a time).

        A rolling refresh assumes the new artifact keeps the OLD
        export contract (feed/fetch names and factors) — mixed
        generations serve side by side mid-deploy. A contract-changing
        model update needs a blue-green fleet swap instead."""
        artifact_dir = str(artifact_dir)
        if not self._deploy_lock.acquire(blocking=False):
            raise FleetError("a rolling deploy is already in progress")
        try:
            return self._rolling_deploy_locked(artifact_dir,
                                               per_replica_timeout_s)
        finally:
            self._deploy_lock.release()

    def _rolling_deploy_locked(self, artifact_dir,
                               per_replica_timeout_s):
        targets = sorted(self.routable())
        if not targets:
            raise FleetError("no live replica to deploy to")
        refreshed = []
        for rid in targets:
            ent = self.routable().get(rid)
            if ent is None:
                continue    # died since the plan was cut: skip it
            if ent.get("dir") == artifact_dir:
                refreshed.append(rid)
                continue
            try:
                status, resp = http_json(
                    "POST", "http://%s/admin/refresh" % ent["addr"],
                    {"dir": artifact_dir}, timeout_s=5.0)
            except (OSError, ValueError) as e:
                raise FleetError("replica %d refused the refresh: %s"
                                 % (rid, e))
            if status != 200:
                raise FleetError("replica %d refused the refresh: %s"
                                 % (rid, resp.get("error", status)))
            deadline = time.monotonic() + float(per_replica_timeout_s)
            back = False
            while time.monotonic() < deadline:
                ent = self.routable().get(rid)
                if ent is not None and ent.get("dir") == artifact_dir:
                    back = True
                    break
                self._stop.wait(0.05)
                if self._stop.is_set():
                    raise FleetError("router closed mid-deploy")
            if not back:
                raise FleetError(
                    "replica %d did not return to rotation on %s "
                    "within %.1fs — deploy stopped (already "
                    "refreshed: %s)" % (rid, artifact_dir,
                                        per_replica_timeout_s,
                                        refreshed))
            refreshed.append(rid)
        with self._meta_lock:
            self._meta = None   # a deploy may change the contract
        record_event("fleet_deploy_complete", refreshed=refreshed,
                     dir=artifact_dir)
        return {"refreshed": refreshed, "dir": artifact_dir}


# ---------------------------------------------------------------------------
# client-side router failover
# ---------------------------------------------------------------------------

class FleetClient(object):
    """Thin fail-over client for the replicated router tier.

    Takes a LIST of router endpoints (``"h:p0,h:p1"``, full URLs, or a
    list of either) and rotates on connection error / 5xx — a router
    SIGKILL costs one rotation, never a failed request. Every request
    carries a fresh random TOKEN; replays (a torn response, a failover
    that loops back to the original router) are IDEMPOTENT router-side
    — the router returns the original request's result instead of
    enqueueing a duplicate. 503 (the whole fleet shedding) and 5xx are
    retried with a tiny backoff until the request deadline, so the
    caller sees an error only when the deadline is truly spent.

    Thread-safe: N load threads may share one client (the chaos
    batteries and ``tools/servingsvc.py client`` do)."""

    def __init__(self, endpoints, request_deadline_s=10.0,
                 backoff_s=0.05, tenant=None, retry_budget=None):
        if isinstance(endpoints, str):
            endpoints = [e.strip() for e in endpoints.split(",")
                         if e.strip()]
        self.urls = [u if "://" in u else "http://" + u
                     for u in endpoints]
        if not self.urls:
            raise ValueError("FleetClient needs at least one router "
                             "endpoint")
        self.request_deadline_s = float(request_deadline_s)
        self._backoff_s = float(backoff_s)
        # QoS identity: rides every request as x-tenant (None = the
        # router's "default" tenant); retry_budget bounds the total
        # replica attempts a request may burn ACROSS hops — it rides
        # as x-retry-budget and bounds this client's own router
        # attempts too, so an outage under load cannot amplify into
        # attempts(client) x attempts(router) retries
        self.tenant = tenant if tenant is None else str(tenant)
        self.retry_budget = retry_budget if retry_budget is None \
            else int(retry_budget)
        if self.retry_budget is not None and self.retry_budget < 1:
            raise ValueError("retry_budget must be >= 1")
        self._lock = threading.Lock()
        self._i = 0

    def _url(self):
        with self._lock:
            return self.urls[self._i % len(self.urls)]

    def _rotate(self):
        with self._lock:
            self._i = (self._i + 1) % len(self.urls)

    def infer(self, feeds, deadline_s=None):
        """One idempotent request against the router tier. Returns the
        response dict ({"outputs", "dtypes", "replica", ...}); raises
        the last error (ConnectionError every router unreachable,
        ServerOverloadedError whole-fleet shed, DeadlineExceededError,
        ValueError for a malformed request — never retried) once the
        deadline is spent.

        With the obs spans engine enabled, each request is the ROOT of
        a distributed trace: the ``client.infer`` span's context rides
        the ``x-trace-id`` header into the router (and on to the
        replica), so ``tools/traceview.py`` can render one client
        request end to end across the fleet's processes."""
        with obs.span("client.infer") as sp:
            return self._infer_traced(feeds, deadline_s, sp)

    def _infer_traced(self, feeds, deadline_s, sp):
        import uuid
        deadline = time.monotonic() + (
            self.request_deadline_s if deadline_s is None
            else float(deadline_s))
        token = uuid.uuid4().hex
        if sp.trace is not None:
            sp.set(token=token)
            if self.tenant is not None:
                sp.set(tenant=self.tenant)
        last_err = None
        attempts = 0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise last_err if last_err is not None else \
                    DeadlineExceededError(
                        "no router answered within the deadline")
            if self.retry_budget is not None \
                    and attempts >= self.retry_budget:
                raise last_err if last_err is not None else \
                    ServerOverloadedError(
                        "retry budget (%d attempts) exhausted"
                        % self.retry_budget)
            url = self._url()
            # the deadline budget is RE-STAMPED per attempt: each hop
            # (and each retry) ships only what is left, so a request
            # that dies in a queue somewhere is refused downstream
            # instead of dispatched into the void
            headers = {"x-deadline-ms": "%d" % int(remaining * 1000.0)}
            if self.tenant is not None:
                headers["x-tenant"] = self.tenant
            if self.retry_budget is not None:
                headers["x-retry-budget"] = \
                    "%d" % (self.retry_budget - attempts)
            if sp.trace is not None:
                headers["x-trace-id"] = "%s:%s" % (sp.trace, sp.id)
            attempts += 1
            try:
                status, resp = http_json(
                    "POST", url + "/infer",
                    {"feeds": feeds, "deadline_s": remaining,
                     "token": token},
                    timeout_s=remaining + 0.5, headers=headers)
            except (OSError, ValueError) as e:
                # a dead/SIGKILLed router: rotate and REPLAY by token
                # (idempotent even when the loop lands back here)
                last_err = ConnectionError(
                    "router %s unreachable: %s" % (url, e))
                self._rotate()
                time.sleep(min(self._backoff_s,
                               max(0.0, deadline - time.monotonic())))
                continue
            if status == 200:
                sp.set(outcome="ok", replica=resp.get("replica"))
                return resp
            if status == 400:
                # malformed request: deterministic on every router —
                # retrying would only burn the deadline
                raise ValueError(resp.get("error", "bad request"))
            if status == 503:
                last_err = ServerOverloadedError(
                    resp.get("error", "fleet is shedding"))
            elif status == 504:
                last_err = DeadlineExceededError(
                    resp.get("error", "fleet deadline"))
            else:
                last_err = RuntimeError(
                    resp.get("error",
                             "router answered HTTP %d" % status))
            self._rotate()
            time.sleep(min(self._backoff_s,
                           max(0.0, deadline - time.monotonic())))


# ---------------------------------------------------------------------------
# replica autoscaling (policy loop on the admission leader)
# ---------------------------------------------------------------------------

class Autoscaler(object):
    """Replica autoscaling policy loop, leader-gated.

    Attached to a :class:`FleetRouter`; every ``interval_s`` it samples
    the router's queue depth, its shed rate (per-router
    ``router_requests_total`` deltas) and the fleet-wide in-flight
    total, over a sliding ``window``. Only the ADMISSION LEADER acts
    (followers keep sampling so a takeover starts warm, but their
    streaks reset on the leadership edge — a new leader must re-observe
    before acting):

      * **grow** — ``hysteresis`` consecutive samples with queue depth
        >= ``grow_queue_depth`` OR window shed rate >=
        ``grow_shed_rate``: the group is RESIZED one slot larger
        (``Coordinator.resize`` — the new slot is born fenced) and
        ``spawner(new_host_id, new_group_size)`` launches the replica,
        which joins through the ordinary announce/admit/join path.
      * **shrink** — a full window of idle samples (zero queue, zero
        in-flight, zero sheds): the HIGHEST grown replica id (only
        slots above the router range are removable — the id space is
        contiguous, so only the top can be resized away) is asked to
        DRAIN (``POST /admin/drain``: it fences itself and stops
        rejoining), waited out of rotation, the group resized one slot
        smaller, and ``stopper(host_id)`` reaps the process.

    Hysteresis + ``cooldown_s`` after every action keep a noisy load
    signal from flapping the fleet. ``min_replicas``/``max_replicas``
    bound the replica tier — max is enforced against ALLOCATED slots
    as well as live replicas, so a spawner whose replicas die before
    joining cannot grow the group without bound; min defaults to the
    base tier (base replicas are permanent members; the resize seam
    only moves the top of the id range). Decisions land as
    ``fleet_autoscale`` events and the ``fleet_target_replicas``
    gauge."""

    def __init__(self, router, spawner=None, stopper=None,
                 min_replicas=None, max_replicas=None,
                 interval_s=0.25, window=8, grow_queue_depth=4.0,
                 grow_shed_rate=0.05, hysteresis=3, cooldown_s=5.0,
                 drain_timeout_s=15.0, grow_high_queue_depth=None):
        self.router = router
        self.spawner = spawner
        self.stopper = stopper
        self.min_replicas = int(min_replicas) \
            if min_replicas is not None else router.n_replicas
        self.max_replicas = int(max_replicas) \
            if max_replicas is not None else router.n_replicas + 4
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                "need 1 <= min_replicas (%d) <= max_replicas (%d)"
                % (self.min_replicas, self.max_replicas))
        self.interval_s = float(interval_s)
        self.window = int(window)
        self.grow_queue_depth = float(grow_queue_depth)
        # class-aware growth: sustained HIGHEST-priority-class queue
        # depth grows the fleet even when brownout shedding keeps the
        # total depth under grow_queue_depth — paying for capacity is
        # the remedy for high-class pressure, shedding is not.
        # Defaults to half the global threshold (min 1) with tenant
        # classes configured; no-op on a classless router (hq == 0)
        self.grow_high_queue_depth = float(grow_high_queue_depth) \
            if grow_high_queue_depth is not None \
            else max(1.0, self.grow_queue_depth / 2.0)
        self.grow_shed_rate = float(grow_shed_rate)
        self.hysteresis = int(hysteresis)
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._samples = collections.deque(maxlen=self.window)
        self._grow_streak = 0
        self._ceiling_warned = False
        self._was_leader = False
        self._last_action_t = None
        self._last_shed = None
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="paddle_tpu-fleet-autoscale-%d"
            % self.router._host_id)
        self._thread.start()
        return self

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.drain_timeout_s + 5.0)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception as e:   # noqa: BLE001 - the loop IS the
                # policy plane: an error costs one tick, not the thread
                record_event("fleet_autoscale_error",
                             error=type(e).__name__)

    # -- signal sampling ---------------------------------------------------
    def _sample(self):
        """One FLEET-WIDE load sample: this router's own queue/shed
        plus every live sibling's, read from their info blobs —
        clients pin one endpoint, so overload routinely lands on a
        FOLLOWER the leader process cannot observe locally. Queue
        depth takes the max (the threshold means "some router's queue
        is this deep"), counters sum."""
        r = self.router
        queue, shed, total = r._load_signals()
        hq = r.high_priority_queue_depth()
        with r._members_lock:
            inflight = sum(r._inflight.values()) \
                + sum(r._peer_inflight.values())
            peers = [dict(v) for v in r._peer_router_load.values()]
        for p in peers:
            queue = max(queue, p.get("queue", 0))
            hq = max(hq, p.get("hq", 0))
            shed += p.get("shed", 0)
            total += p.get("reqs", 0)
        return {"queue": queue, "shed": shed,
                "total": total, "inflight": inflight, "hq": hq}

    def _window_shed_rate(self):
        if len(self._samples) < 2:
            return 0.0
        first, last = self._samples[0], self._samples[-1]
        d_total = last["total"] - first["total"]
        d_shed = last["shed"] - first["shed"]
        return d_shed / float(d_total) if d_total > 0 else 0.0

    def _tick(self):
        leader = self.router.is_leader()
        if leader != self._was_leader:
            # leadership edge: a fresh leader re-observes before it
            # may act — inherited streaks belong to another router
            self._grow_streak = 0
            self._samples.clear()
            self._was_leader = leader
        s = self._sample()
        self._samples.append(s)
        if s["queue"] >= self.grow_queue_depth \
                or s["hq"] >= self.grow_high_queue_depth \
                or (len(self._samples) >= 2
                    and self._window_shed_rate()
                    >= self.grow_shed_rate):
            self._grow_streak += 1
        else:
            self._grow_streak = 0
        if not leader:
            return
        if self._last_action_t is not None and \
                time.monotonic() - self._last_action_t \
                < self.cooldown_s:
            return
        live = sorted(self.router.routable())
        n_live = len(live)
        if self._grow_streak >= self.hysteresis \
                and n_live < self.max_replicas:
            self._grow(n_live)
        elif len(self._samples) == self.window \
                and all(x["queue"] == 0 and x["inflight"] == 0
                        for x in self._samples) \
                and self._samples[-1]["shed"] \
                == self._samples[0]["shed"]:
            if n_live > self.min_replicas:
                self._shrink(live)
            else:
                # even at the live floor an idle window may have a
                # LEFTOVER to reap: a fenced top slot holds no live
                # replica, so it never counts toward n_live but wedges
                # all future scale-in until resized away
                self._reclaim(live)

    # -- actuation ---------------------------------------------------------
    def _group_size(self):
        try:
            m = self.router._co.members()
        except (CoordinationError, ConnectionError):
            return None
        return m.get("n_hosts")

    def _resize_with_retry(self, n_hosts, action, budget_s=5.0):
        """The fleet's control rounds tick continuously, so a resize
        routinely races an open gather ("refused mid-round") — rounds
        live milliseconds, so a short retry loop rides them out. A
        bounded failure here matters most on SHRINK, where the victim
        already drained: bailing would orphan it out of rotation with
        its slot still counted."""
        deadline = time.monotonic() + float(budget_s)
        while True:
            try:
                self.router._co.resize(int(n_hosts))
                return True
            except (CoordinationError, ConnectionError) as e:
                if time.monotonic() >= deadline:
                    record_event("fleet_autoscale_deferred",
                                 action=action,
                                 error=type(e).__name__)
                    return False
                if self._stop.wait(0.05):
                    return False

    def _grow(self, n_live):
        group = self._group_size()
        if group is None:
            return
        if int(group) - self.router.n_routers >= self.max_replicas:
            # every replica SLOT is already allocated — n_live only
            # counts joined replicas, so gating on it alone would let
            # sustained pressure over a broken spawner grow the group
            # one fenced phantom slot per cooldown without bound
            if not self._ceiling_warned:
                self._ceiling_warned = True
                record_event("fleet_autoscale_deferred", action="grow",
                             error="replica_slot_ceiling",
                             group=int(group))
            return
        self._ceiling_warned = False
        new_id, new_group = int(group), int(group) + 1
        if not self._resize_with_retry(new_group, "grow"):
            # hysteresis already proved the pressure: next tick retries
            return
        self._last_action_t = time.monotonic()
        self._grow_streak = 0
        record_event("fleet_autoscale", action="grow",
                     target=n_live + 1, member=new_id,
                     group=new_group)
        if self.spawner is not None:
            self.spawner(new_id, new_group)

    def _shrink(self, live):
        group = self._group_size()
        if group is None:
            return
        victim = int(group) - 1
        # only the TOP id is removable (contiguous id space), and only
        # GROWN slots above the router range may leave — the base tier
        # is permanent membership
        if victim < self.router.n_replicas + self.router.n_routers:
            return
        if victim not in live:
            self._reclaim(live)
            return
        ent = self.router.routable().get(victim)
        if ent is None:
            return
        try:
            status, resp = http_json(
                "POST", "http://%s/admin/drain" % ent["addr"], {},
                timeout_s=5.0)
        except (OSError, ValueError):
            return                   # unreachable: retry next window
        if status != 200:
            return
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            if victim not in self.router.routable():
                break
            if self._stop.wait(0.05):
                return
        else:
            record_event("fleet_autoscale_deferred", action="shrink",
                         error="drain_timeout", member=victim)
            return
        if not self._resize_with_retry(int(group) - 1, "shrink"):
            return
        self._last_action_t = time.monotonic()
        self._samples.clear()
        record_event("fleet_autoscale", action="shrink",
                     target=len(live) - 1, member=victim,
                     group=int(group) - 1)
        if self.stopper is not None:
            self.stopper(victim)

    def _reclaim(self, live):
        """Reap a fenced, unroutable TOP slot — the leftover that
        otherwise wedges ALL future scale-in (only the top id is
        removable, and a fenced slot can never become live on its
        own): a drain whose follow-up resize exhausted its budget, or
        a grown replica that died before joining. Only a slot the
        coordinator confirms FENCED is reclaimed — anything holding a
        live-looking lease is left alone, and a joiner racing the
        resize loses to the stale-size named error, never a phantom
        membership."""
        try:
            m = self.router._co.members()
        except (CoordinationError, ConnectionError):
            return
        group = m.get("n_hosts")
        if group is None:
            return
        victim = int(group) - 1
        if victim < self.router.n_replicas + self.router.n_routers \
                or victim in live \
                or victim not in m.get("lost", {}):
            return
        if not self._resize_with_retry(int(group) - 1, "shrink"):
            return
        self._last_action_t = time.monotonic()
        self._samples.clear()
        record_event("fleet_autoscale", action="shrink",
                     target=len(live), member=victim,
                     group=int(group) - 1, reclaimed=True)
        if self.stopper is not None:
            self.stopper(victim)
