"""Module-path alias for fluid.op (ref python/paddle/fluid/op.py):
operator construction is Program IR here."""
from .framework.program import Operator  # noqa: F401

__all__ = ["Operator"]
