"""Neural-net op kernels: conv, pool, normalization, losses, embedding.

Reference parity: paddle/fluid/operators/{conv_op,pool_op,batch_norm_op,
layer_norm_op,group_norm_op,instance_norm_op,softmax_op,cross_entropy_op,
softmax_with_cross_entropy_op,dropout_op,lookup_table_op,...}. The reference
dispatches to cuDNN; here the kernels are lax convolution/reduce-window
primitives that XLA maps onto the MXU directly.
"""
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from . import pallas_dispatch as _pd
from ..framework.dtypes import to_jax_dtype


def _x(ins, slot="X"):
    return ins[slot][0]


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------

@register_op("conv2d")
def _conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    out = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.float32
        if x.dtype == jnp.bfloat16 else None)
    out = out.astype(x.dtype)
    return {"Output": out}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    return _conv2d(ctx, ins, attrs)


def _conv_transpose_nd(x, w, strides, pads, dil, groups, dn, out_sp=None):
    """Fluid's conv_transpose IS the input-gradient of the forward conv
    (ref conv_transpose_op.h computes it with col2im); building it as the
    actual vjp of lax.conv_general_dilated is exact for every
    stride/padding/dilation/groups combination and stays differentiable
    (vjp-of-vjp). Filter layout: (in_c, out_c/g, *k). out_sp overrides the
    derived spatial output size (ref output_size attr) — any size whose
    forward conv maps back to x's extent is valid."""
    k_sp = w.shape[2:]
    if out_sp is None:
        out_sp = tuple(
            (x.shape[2 + i] - 1) * strides[i] - 2 * pads[i] +
            dil[i] * (k_sp[i] - 1) + 1 for i in range(len(k_sp)))
    out_shape = (x.shape[0], w.shape[1] * groups) + out_sp

    def fwd(y):
        return lax.conv_general_dilated(
            y, w, window_strides=strides,
            padding=[(p, p) for p in pads], rhs_dilation=dil,
            feature_group_count=groups, dimension_numbers=dn)

    _, vjp = jax.vjp(fwd, jnp.zeros(out_shape, x.dtype))
    return vjp(x)[0]


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    out_sp = attrs.get("output_size") or None
    out = _conv_transpose_nd(x, w, strides, pads, dil, groups,
                             ("NCHW", "OIHW", "NCHW"),
                             out_sp=None if out_sp is None
                             else tuple(out_sp))
    return {"Output": out}


@register_op("conv3d")
def _conv3d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = tuple(attrs.get("strides", [1, 1, 1]))
    pads = tuple(attrs.get("paddings", [0, 0, 0]))
    dil = tuple(attrs.get("dilations", [1, 1, 1]))
    groups = attrs.get("groups", 1) or 1
    out = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads], rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return {"Output": out}


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

@register_op("pool2d")
def _pool2d(ctx, ins, attrs):
    x = _x(ins)
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False) or (
            attrs.get("adaptive", False) and
            tuple(attrs.get("ksize", [1, 1])) == (1, 1)):
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": fn(x, axis=(2, 3), keepdims=True)}
    ks = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", ks))
    pads = _pair(attrs.get("paddings", [0, 0]))
    if attrs.get("adaptive", False):
        oh, ow = _pair(attrs["ksize"])
        h, w = x.shape[2], x.shape[3]
        if h % oh or w % ow:
            raise NotImplementedError(
                "adaptive pool2d needs input divisible by output size "
                "(got %sx%s -> %sx%s)" % (h, w, oh, ow))
        x5 = x.reshape(x.shape[0], x.shape[1], oh, h // oh, ow, w // ow)
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": fn(x5, axis=(3, 5))}
    window = (1, 1) + ks
    strides4 = (1, 1) + strides
    padding = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides4, padding)
    else:
        s = lax.reduce_window(x, 0.0, lax.add, window, strides4, padding)
        if attrs.get("exclusive", True):
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides4,
                                    padding)
            out = s / cnt
        else:
            out = s / (ks[0] * ks[1])
    return {"Out": out}


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

@register_op("batch_norm", nondiff=("Mean", "Variance"))
def _batch_norm(ctx, ins, attrs):
    x = _x(ins)
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    layout = attrs.get("data_layout", "NCHW")
    is_test = attrs.get("is_test", False)
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    if is_test or attrs.get("use_global_stats", False):
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean, saved_var = mean, var
    else:
        xf = x.astype(jnp.float32)
        use_mean = jnp.mean(xf, axis=axes)
        use_var = jnp.var(xf, axis=axes)
        mean_out = mean * momentum + use_mean * (1 - momentum)
        var_out = var * momentum + use_var * (1 - momentum)
        saved_mean, saved_var = use_mean, use_var
    inv = lax.rsqrt(use_var.astype(jnp.float32) + eps)
    y = (x.astype(jnp.float32) - use_mean.reshape(bshape)) * \
        (inv * scale.astype(jnp.float32)).reshape(bshape) + \
        bias.astype(jnp.float32).reshape(bshape)
    return {"Y": y.astype(x.dtype),
            "MeanOut": lax.stop_gradient(mean_out),
            "VarianceOut": lax.stop_gradient(var_out),
            "SavedMean": lax.stop_gradient(saved_mean),
            "SavedVariance": lax.stop_gradient(saved_var)}


def _pallas_layer_norm(x, ins, eps, begin, cfg):
    """BuildStrategy.use_pallas={"layer_norm"}: fused one-pass Pallas
    fwd+bwd over the collapsed (rows, cols) problem. Returns the op's
    output dict, or None when the autotune cache routed this shape back
    to XLA / the shape cannot tile — caller keeps the XLA lowering.
    Mean/Variance are emitted as a standalone (cheap, per-row) XLA
    expression that DCEs away when unused, exactly like the XLA path's
    values."""
    from .pallas.layer_norm import fused_layer_norm
    rows = int(np.prod(x.shape[:begin], dtype=np.int64)) if begin else 1
    cols = int(np.prod(x.shape[begin:], dtype=np.int64))
    x2 = x.reshape(rows, cols)
    impl, tuned = _pd.choose(cfg, "layer_norm", x2.shape, x2.dtype)
    if impl == "xla":
        return None
    y = fused_layer_norm(
        x2, ins["Scale"][0].reshape(cols), ins["Bias"][0].reshape(cols),
        eps=eps, interpret=cfg.interpret, **(tuned or {}))
    if y is None:
        return None
    xf = x.astype(jnp.float32)
    axes = tuple(range(begin, x.ndim))
    return {"Y": y.reshape(x.shape).astype(x.dtype),
            "Mean": jnp.mean(xf, axis=axes),
            "Variance": jnp.var(xf, axis=axes)}


@register_op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = _x(ins)
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    cfg = _pd.enabled("layer_norm")
    if cfg is not None and ins.get("Scale") and ins.get("Bias"):
        out = _pallas_layer_norm(x, ins, eps, begin, cfg)
        if out is not None:
            return out
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    norm_shape = x.shape[begin:]
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(norm_shape).astype(jnp.float32)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(norm_shape).astype(jnp.float32)
    return {"Y": y.astype(x.dtype),
            "Mean": mean.reshape(x.shape[:begin]),
            "Variance": var.reshape(x.shape[:begin])}


@register_op("group_norm")
def _group_norm(ctx, ins, attrs):
    x = _x(ins)  # NCHW
    g = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = [1, c] + [1] * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape)
    return {"Y": y, "Mean": mean.reshape(n, g), "Variance": var.reshape(n, g)}


@register_op("instance_norm")
def _instance_norm(ctx, ins, attrs):
    x = _x(ins)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    c = x.shape[1]
    bshape = [1, c] + [1] * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape)
    return {"Y": y, "SavedMean": mean, "SavedVariance": var}


@register_op("l2_normalize")
def _l2_normalize(ctx, ins, attrs):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return {"Out": x / jnp.maximum(norm, eps), "Norm": norm}


# ---------------------------------------------------------------------------
# softmax & losses
# ---------------------------------------------------------------------------

@register_op("softmax")
def _softmax(ctx, ins, attrs):
    return {"Out": jax.nn.softmax(_x(ins), axis=attrs.get("axis", -1))}


@register_op("log_softmax")
def _log_softmax(ctx, ins, attrs):
    return {"Out": jax.nn.log_softmax(_x(ins), axis=attrs.get("axis", -1))}


@register_op("cross_entropy", nondiff=("Label",))
def _cross_entropy(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), axis=-1,
                        keepdims=True)
    else:
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 \
            else label
        picked = jnp.take_along_axis(
            x, lbl[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(jnp.maximum(picked, 1e-20))
        ignore = attrs.get("ignore_index", -100)
        loss = jnp.where(lbl[..., None] == ignore, 0.0, loss)
    return {"Y": loss}


def _pallas_softmax_ce(logits, lbl, attrs, cfg):
    """BuildStrategy.use_pallas={"softmax_with_cross_entropy"}: the loss
    streams over vocab blocks (ops/pallas/blockwise_ce) — no
    [tokens, vocab] log-softmax/softmax intermediate in fwd or bwd.
    Returns the per-token loss (lbl.shape + (1,), f32), or None when
    the autotune cache routed this shape to XLA / it cannot tile."""
    from .pallas.blockwise_ce import blockwise_softmax_cross_entropy
    v = logits.shape[-1]
    l2 = logits.reshape(-1, v)
    impl, tuned = _pd.choose(cfg, "softmax_with_cross_entropy",
                             l2.shape, l2.dtype)
    if impl == "xla":
        return None
    loss = blockwise_softmax_cross_entropy(
        l2, lbl.reshape(-1).astype(jnp.int32), interpret=cfg.interpret,
        **(tuned or {}))
    if loss is None:
        return None
    loss = loss.reshape(lbl.shape)[..., None]
    ignore = attrs.get("ignore_index", -100)
    return jnp.where(lbl[..., None] == ignore, 0.0, loss)


@register_op("softmax_with_cross_entropy", nondiff=("Label",))
def _softmax_with_cross_entropy(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    axis = attrs.get("axis", -1)
    if not attrs.get("soft_label", False):
        lbl = label
        squeeze = lbl.ndim == logits.ndim and lbl.shape[axis] == 1
        if squeeze:
            lbl = jnp.squeeze(lbl, axis=axis)
        cfg = _pd.enabled("softmax_with_cross_entropy")
        if cfg is not None and logits.ndim >= 2 and \
                axis in (-1, logits.ndim - 1) and \
                lbl.ndim == logits.ndim - 1:
            loss = _pallas_softmax_ce(logits, lbl, attrs, cfg)
            if loss is not None:
                # Softmax is a STANDALONE XLA expression: when the
                # output is unused (the MLM-loss case) XLA DCEs it and
                # only the blockwise kernels remain — same pattern as
                # the flash-attention mask cotangent
                logp = jax.nn.log_softmax(
                    logits.astype(jnp.float32), axis=axis)
                return {"Softmax": jnp.exp(logp).astype(logits.dtype),
                        "Loss": loss.astype(logits.dtype)}
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        squeeze = lbl.ndim == logits.ndim and lbl.shape[axis] == 1
        if squeeze:
            lbl = jnp.squeeze(lbl, axis=axis)
        picked = jnp.take_along_axis(
            logp, lbl[..., None].astype(jnp.int32), axis=axis)
        loss = -picked
        ignore = attrs.get("ignore_index", -100)
        loss = jnp.where(lbl[..., None] == ignore, 0.0, loss)
    return {"Softmax": jnp.exp(logp).astype(logits.dtype),
            "Loss": loss.astype(logits.dtype)}


@register_op("fused_mlm_head_loss", nondiff=("Label",))
def _fused_mlm_head_loss(ctx, ins, attrs):
    """LM/MLM head + softmax CE in one op: ``Hidden (T, D) @ Weight^T
    (+ Bias) -> per-token Loss (T, 1)`` — the model-head fusion seam.
    Behind ``BuildStrategy.use_pallas={"fused_mlm_head_loss"}`` the op
    routes to ops/pallas/blockwise_ce.fused_mlm_head_loss and the
    ``[tokens, vocab]`` logits NEVER materialize in fwd or bwd; the XLA
    fallback mirrors the matmul + softmax_with_cross_entropy chain it
    replaces in models/bert + models/gpt (same math, so the wiring is
    loss-curve-neutral with Pallas off).

    Weight is the (V, D) tied embedding table (``transpose_y=True``
    matmul layout); attr ``cast_bf16`` runs the projection in bf16 with
    f32 accumulation (the _mlm_decode trick)."""
    hidden, weight = ins["Hidden"][0], ins["Weight"][0]
    label = ins["Label"][0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    lbl = label.reshape(label.shape[:-1]) if label.ndim > 1 and \
        label.shape[-1] == 1 else label
    h, w = hidden, weight
    if attrs.get("cast_bf16", False):
        h = h.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    # also honor use_pallas={"softmax_with_cross_entropy"}: configs that
    # enabled the blockwise-CE kernel for the (pre-PR-10, unfused) model
    # heads keep their Pallas routing now that the heads emit this op —
    # the fusion is strictly stronger than what they asked for. (Their
    # autotune entries keyed under the old op name simply miss: default
    # blocks apply until a re-sweep.)
    cfg = _pd.enabled("fused_mlm_head_loss") or \
        _pd.enabled("softmax_with_cross_entropy")
    if cfg is not None and hidden.ndim == 2 and lbl.ndim == 1:
        from .pallas.blockwise_ce import fused_mlm_head_loss
        impl, tuned = _pd.choose(cfg, "fused_mlm_head_loss",
                                 (h.shape[0], weight.shape[0]), h.dtype)
        if impl == "pallas_q":
            # the banked QUANTIZED variant: bf16-cast projection inputs
            # with f32 accumulation (the cast_bf16 trick, selected per
            # call site by a measured sweep verdict instead of a model
            # attr)
            h = h.astype(jnp.bfloat16)
            w = w.astype(jnp.bfloat16)
        if impl != "xla":
            loss = fused_mlm_head_loss(
                h, w.T, lbl.astype(jnp.int32),
                bias=None if bias is None else bias.astype(jnp.float32),
                interpret=cfg.interpret, **(tuned or {}))
            if loss is not None:
                return {"Loss": loss[:, None].astype(jnp.float32)}
    # XLA fallback: the exact chain the models used to emit — matmul
    # (transpose_y, f32 accumulation under cast_bf16) + bias +
    # log_softmax gather
    logits = jnp.matmul(h, w.T,
                        preferred_element_type=jnp.float32) \
        .astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(
        logp, lbl[..., None].astype(jnp.int32), axis=-1)
    return {"Loss": -picked}


@register_op("sigmoid_cross_entropy_with_logits", nondiff=("Label",))
def _sigmoid_ce(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if attrs.get("normalize", False):
        n = jnp.maximum(jnp.sum(label != ignore), 1)
        loss = loss / n
    return {"Out": loss}


@register_op("square_error_cost")
def _square_error_cost(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.square(x - y)}


@register_op("smooth_l1_loss", nondiff=("Y",))
def _smooth_l1(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    if ins.get("InsideWeight"):
        d = d * ins["InsideWeight"][0]
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
    if ins.get("OutsideWeight"):
        loss = loss * ins["OutsideWeight"][0]
    return {"Out": jnp.sum(loss, axis=tuple(range(1, x.ndim)),
                           keepdims=False)[..., None],
            "Diff": d}


@register_op("huber_loss", nondiff=("Y",))
def _huber(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    d = y - x
    ad = jnp.abs(d)
    loss = jnp.where(ad <= delta, 0.5 * d * d,
                     delta * (ad - 0.5 * delta))
    return {"Out": loss, "Residual": d}


@register_op("log_loss", nondiff=("Labels",))
def _log_loss(ctx, ins, attrs):
    p, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    return {"Loss": -label * jnp.log(p + eps) -
            (1 - label) * jnp.log(1 - p + eps)}


@register_op("kldiv_loss", nondiff=("Target",))
def _kldiv(ctx, ins, attrs):
    x, target = ins["X"][0], ins["Target"][0]
    loss = target * (jnp.log(jnp.maximum(target, 1e-20)) - x)
    loss = jnp.where(target <= 0, 0.0, loss)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    return {"Loss": loss}


@register_op("bpr_loss", nondiff=("Label",))
def _bpr_loss(ctx, ins, attrs):
    """loss_i = -(1/(C-1)) * sum_{j != label_i} log sigmoid(x_pos - x_j)
    (ref bpr_loss_op.h:63-77: the positive item's logit minus each
    NEGATIVE's, label column excluded from the sum). The round-5 oracle
    sweep caught this kernel with the sigmoid argument flipped and the
    label term included at 1/C weight."""
    x, label = ins["X"][0], ins["Label"][0]
    n, c = x.shape
    lbl = label.reshape(n).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lbl[:, None], axis=1)
    logsig = jax.nn.log_sigmoid(pos - x)          # (N, C)
    neg_mask = 1.0 - jax.nn.one_hot(lbl, c, dtype=x.dtype)
    loss = -jnp.sum(logsig * neg_mask, axis=1, keepdims=True) / (c - 1)
    return {"Y": loss}


@register_op("margin_rank_loss", nondiff=("Label",))
def _margin_rank(ctx, ins, attrs):
    x1, x2, label = ins["X1"][0], ins["X2"][0], ins["Label"][0]
    margin = attrs.get("margin", 0.0)
    out = jax.nn.relu(-label * (x1 - x2) + margin)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("label_smooth", nondiff=("PriorDist",))
def _label_smooth(ctx, ins, attrs):
    x = _x(ins)
    eps = attrs.get("epsilon", 0.0)
    k = x.shape[-1]
    if ins.get("PriorDist"):
        prior = ins["PriorDist"][0]
        return {"Out": (1 - eps) * x + eps * prior}
    return {"Out": (1 - eps) * x + eps / k}


@register_op("mse_loss", nondiff=("Label",))
def _mse(ctx, ins, attrs):
    x, label = ins["Input"][0], ins["Label"][0]
    return {"Out": jnp.square(x - label)}


# ---------------------------------------------------------------------------
# embedding (reference: lookup_table_op.cc; grads become scatter-adds which
# XLA turns into efficient TPU one-hot matmuls / dynamic-update fusions)
# ---------------------------------------------------------------------------

@register_op("lookup_table", nondiff=("Ids",))
def _lookup_table(ctx, ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    squeeze = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze:
        ids = ids.reshape(ids.shape[:-1])
    ids = ids.astype(jnp.int32)
    padding_idx = attrs.get("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return {"Out": out}


@register_op("lookup_table_v2", nondiff=("Ids",))
def _lookup_table_v2(ctx, ins, attrs):
    return _lookup_table(ctx, ins, attrs)


@register_op("one_hot", nondiff=("X",))
def _one_hot(ctx, ins, attrs):
    x = _x(ins)
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    return {"Out": jax.nn.one_hot(x.astype(jnp.int32), attrs["depth"],
                                  dtype=to_jax_dtype(
                                      attrs.get("dtype", "float32")))}


# ---------------------------------------------------------------------------
# dropout & friends
# ---------------------------------------------------------------------------

_RBG_PROBE = {}


def _rbg_supported():
    """One eager probe per backend: RngBitGenerator availability surfaces
    at COMPILE time, so a trace-time try/except around the traced op could
    never catch it — run a tiny real computation once instead."""
    backend = jax.default_backend()
    ok = _RBG_PROBE.get(backend)
    if ok is None:
        try:
            k = jax.random.wrap_key_data(jnp.zeros(4, jnp.uint32),
                                         impl="rbg")
            np.asarray(jax.random.bernoulli(k, 0.5, (8,)))
            ok = True
        except Exception:
            ok = False
        _RBG_PROBE[backend] = ok
    return ok


def _fast_keep_mask(key, p_keep, shape):
    """Bernoulli(p_keep) via the hardware RNG ('rbg' PRNG impl):
    counter-based threefry costs ~40% of a BERT-base train step in
    per-layer mask generation (measured 1014 -> 1416 samples/s on v5e with
    dropout off); the HW generator makes masks nearly free. Masks stay
    deterministic per (key, backend, compilation) — the per-op key
    derivation in framework/trace.py is unchanged — but unlike threefry
    the bits are NOT invariant across shardings/compilations (the same
    trade T5X/praxis make with unsafe_rbg). PADDLE_TPU_FAST_DROPOUT=0
    restores fully sharding-invariant threefry masks."""
    import os
    if os.environ.get("PADDLE_TPU_FAST_DROPOUT", "1") in ("0", "false"):
        return jax.random.bernoulli(key, p_keep, shape)
    if not _rbg_supported():
        return jax.random.bernoulli(key, p_keep, shape)
    kd = jax.random.key_data(key).reshape(-1).astype(jnp.uint32)
    k4 = jnp.concatenate([kd, kd])[:4]
    rbg_key = jax.random.wrap_key_data(k4, impl="rbg")
    return jax.random.bernoulli(rbg_key, p_keep, shape)


@register_op("dropout", uses_rng=True)
def _dropout(ctx, ins, attrs):
    x = _x(ins)
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        if impl == "upscale_in_train":
            return {"Out": x, "Mask": jnp.ones_like(x, dtype=jnp.uint8)}
        return {"Out": x * (1.0 - p),
                "Mask": jnp.ones_like(x, dtype=jnp.uint8)}
    if p <= 0.0:
        return {"Out": x, "Mask": jnp.ones_like(x, dtype=jnp.uint8)}
    keep = _fast_keep_mask(ctx.rng(), 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0)
    else:
        out = jnp.where(keep, x, 0.0)
    return {"Out": out.astype(x.dtype), "Mask": keep.astype(jnp.uint8)}


@register_op("pad")
def _pad(ctx, ins, attrs):
    x = _x(ins)
    paddings = attrs["paddings"]
    pv = attrs.get("pad_value", 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, cfg, constant_values=pv)}


@register_op("pad2d")
def _pad2d(ctx, ins, attrs):
    x = _x(ins)
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    cfg = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        return {"Out": jnp.pad(x, cfg,
                               constant_values=attrs.get("pad_value", 0.0))}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, cfg, mode=jmode)}


def _interp_src(out_size, in_size, align_corners, align_mode):
    """Source sampling coordinates for one axis — the reference's three
    conventions (interpolate_op.h:80-163): align_corners uses the
    (in-1)/(out-1) corner-pinned ratio; otherwise ratio=in/out with
    align_mode 0 = half-pixel centers, align_mode 1 = src = ratio*dst."""
    i = jnp.arange(out_size, dtype=jnp.float32)
    if align_corners:
        return i * ((in_size - 1) / max(out_size - 1, 1))
    ratio = in_size / out_size
    if align_mode == 0:
        return jnp.clip((i + 0.5) * ratio - 0.5, 0.0, in_size - 1.0)
    return i * ratio


def _lin_axis(x, out_size, axis, align_corners, align_mode):
    in_size = x.shape[axis]
    src = _interp_src(out_size, in_size, align_corners, align_mode)
    lo = jnp.floor(src).astype(jnp.int32)
    lo = jnp.clip(lo, 0, in_size - 1)
    hi = jnp.minimum(lo + 1, in_size - 1)
    # interpolate in float regardless of input dtype (an integer x would
    # truncate the fractions to pure floor-sampling); cast back at the end
    ft = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    d = (src - lo).astype(ft)
    shape = [1] * x.ndim
    shape[axis] = out_size
    d = d.reshape(shape)
    out = (jnp.take(x, lo, axis=axis).astype(ft) * (1 - d) +
           jnp.take(x, hi, axis=axis).astype(ft) * d)
    return out.astype(x.dtype)


@register_op("interp_nearest", nondiff=())
def _interp_nearest(ctx, ins, attrs):
    x = _x(ins)
    oh, ow = attrs["out_h"], attrs["out_w"]
    ac = attrs.get("align_corners", True)
    out = x
    for axis, osz in ((2, oh), (3, ow)):
        in_size = out.shape[axis]
        if ac:
            # reference: src = int(ratio*dst + 0.5), corner-pinned ratio
            idx = jnp.floor(_interp_src(osz, in_size, True, 1)
                            + 0.5).astype(jnp.int32)
        else:
            idx = jnp.floor(_interp_src(osz, in_size, False, 1)
                            ).astype(jnp.int32)
        out = jnp.take(out, jnp.clip(idx, 0, in_size - 1), axis=axis)
    return {"Out": out}


@register_op("interp_bilinear", nondiff=())
def _interp_bilinear(ctx, ins, attrs):
    x = _x(ins)
    oh, ow = attrs["out_h"], attrs["out_w"]
    ac = attrs.get("align_corners", True)
    am = attrs.get("align_mode", 1)
    out = _lin_axis(x, oh, 2, ac, am)
    out = _lin_axis(out, ow, 3, ac, am)
    return {"Out": out}


@register_op("add_position_encoding")
def _add_position_encoding(ctx, ins, attrs):
    x = _x(ins)  # (N, L, D)
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    n, l, d = x.shape
    # pos_offset: incremental decode adds the encoding for absolute position
    # t to a single-token slice (KV-cache path)
    pos = (jnp.arange(l, dtype=jnp.float32)
           + float(attrs.get("pos_offset", 0)))[:, None]
    i = jnp.arange(d // 2, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, 2 * i / d)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=-1)
    return {"Out": alpha * x + beta * pe[None, :, :].astype(x.dtype)}
