"""Quantization op kernels + the block-quantization codec.

Reference parity: paddle/fluid/operators/fake_quantize_op.cc + the
contrib/slim quantization passes. Simulated quantization: values are
quantized->dequantized in fp so XLA still runs bf16/fp32 matmuls; gradients
pass straight through (STE), expressed exactly as
x + stop_gradient(qdq(x) - x).

Block codec (EQuARX, PAPERS.md): the bandwidth-bound paths — data-parallel
gradient all-reduce (ops/collective_ops.quantized_psum), elastic rejoin
state shipping (coordination.ElasticTrainer) and checkpoint payloads
(io.save_checkpoint(compress=)) — move int8 payloads with one fp32 scale
per ``block_size`` values instead of full-width floats:

  * :func:`block_quantize` / :func:`block_dequantize` — the traced (jnp)
    halves, static shapes, jit/shard_map-safe. Per-block abs-max scaling:
    the max-magnitude element of every block round-trips exactly, every
    other element is within ``absmax_block / qmax / 2`` of its value, and
    any non-finite input poisons its whole block to NaN (so check_numerics
    still fires instead of silently training on garbage).
  * :func:`encode_array` / :func:`decode_array` — the host (numpy) codec
    for state movement. mode="zlib" is LOSSLESS (bitwise round-trip; the
    default for param/optimizer state, whose exactness guarantees must
    survive the wire); mode="q8" is the lossy block codec (same error
    envelope as the collective path).
  * :func:`quantized_wire_bytes` — the raw-vs-wire byte accounting behind
    the ``*_bytes_total`` counters in ``resilience.metrics()``.
"""
import zlib

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op

# codec defaults shared by collectives, state-ship and checkpoints
DEFAULT_BLOCK_SIZE = 256
DEFAULT_BITS = 8
SCALE_BYTES = 4          # one fp32 scale per block
_SCALE_FLOOR = 1e-12     # all-zero blocks: avoid 0/0 without moving values


def _qdq_abs_max(x, bits, scale=None):
    qmax = 2.0 ** (bits - 1) - 1
    if scale is None:
        scale = jnp.max(jnp.abs(x))
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax - 1, qmax)
    return q * scale / qmax, scale


@register_op("fake_quantize_dequantize_abs_max")
def _fake_qdq_abs_max(ctx, ins, attrs):
    """Per-tensor abs-max sim-quant with STE gradient (reference
    fake_quantize_dequantize_abs_max op)."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    qdq, scale = _qdq_abs_max(x, bits)
    out = x + jax.lax.stop_gradient(qdq - x)
    return {"Out": out, "OutScale": scale[None]}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             nondiff=("InScale", "InState", "InAccum"))
def _fake_qdq_moving_avg(ctx, ins, attrs):
    """Moving-average abs-max sim-quant (reference
    fake_quantize_dequantize_moving_average_abs_max): scale tracks
    rate * state + abs_max running average; STE gradient."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    rate = float(attrs.get("moving_rate", 0.9))
    state = ins["InState"][0] if ins.get("InState") else jnp.ones((1,))
    accum = ins["InAccum"][0] if ins.get("InAccum") else jnp.zeros((1,))
    cur = jnp.max(jnp.abs(x))
    new_state = rate * state + 1.0
    new_accum = rate * accum + cur
    scale = new_accum / new_state
    qdq, _ = _qdq_abs_max(x, bits, scale.reshape(()))
    out = x + jax.lax.stop_gradient(qdq - x)
    return {"Out": out, "OutScale": scale.reshape(1),
            "OutState": new_state, "OutAccum": new_accum}


# ---------------------------------------------------------------------------
# block codec — traced (jnp) halves
# ---------------------------------------------------------------------------

def _qmax(bits):
    return 2.0 ** (int(bits) - 1) - 1


def block_quantize(x, block_size=DEFAULT_BLOCK_SIZE, bits=DEFAULT_BITS):
    """Quantize ``x`` into int8 blocks with per-block fp32 abs-max scales.

    Returns ``(q, scale)`` where ``q`` is ``(n_blocks, block_size)`` int8
    (the flattened input zero-padded to a whole number of blocks) and
    ``scale`` is ``(n_blocks,)`` float32. Static shapes — safe inside
    jit/shard_map/scan. A non-finite element makes its block's scale
    non-finite, which :func:`block_dequantize` turns into an all-NaN
    block: poison is preserved, never silently clipped to finite values.
    """
    qmax = _qmax(bits)
    flat = jnp.ravel(x)
    n = flat.shape[0]
    pad = (-n) % int(block_size)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    blocks = flat.reshape(-1, int(block_size)).astype(jnp.float32)
    scale = jnp.max(jnp.abs(blocks), axis=1)
    safe = jnp.maximum(scale, _SCALE_FLOOR)
    q = jnp.clip(jnp.round(blocks / safe[:, None] * qmax), -qmax, qmax)
    return q.astype(jnp.int8), scale


def block_dequantize(q, scale, shape, dtype, bits=DEFAULT_BITS):
    """Inverse of :func:`block_quantize`: rebuild an array of
    ``shape``/``dtype`` from int8 blocks + fp32 scales."""
    qmax = _qmax(bits)
    safe = jnp.maximum(scale, _SCALE_FLOOR)
    blocks = q.astype(jnp.float32) * (safe / qmax)[:, None]
    size = int(np.prod(shape)) if shape else 1
    return blocks.reshape(-1)[:size].reshape(shape).astype(dtype)


def quantized_wire_bytes(size, itemsize, block_size=DEFAULT_BLOCK_SIZE,
                         bits=DEFAULT_BITS):
    """(raw, wire) byte accounting of one quantized transfer: ``raw`` is
    what the full-width collective/copy would move, ``wire`` the int8
    payload plus one fp32 scale per block."""
    size = int(size)
    n_blocks = -(-size // int(block_size)) if size else 0
    payload = n_blocks * int(block_size) * (int(bits) // 8)
    return size * int(itemsize), payload + n_blocks * SCALE_BYTES


# ---------------------------------------------------------------------------
# host codec — state movement (numpy; in-process metadata, never pickled)
# ---------------------------------------------------------------------------

def np_block_quantize(arr, block_size=DEFAULT_BLOCK_SIZE,
                      bits=DEFAULT_BITS):
    """Numpy mirror of :func:`block_quantize` (checkpoint payloads and
    host-side state shipping)."""
    qmax = _qmax(bits)
    flat = np.asarray(arr, np.float32).reshape(-1)
    pad = (-flat.size) % int(block_size)
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    blocks = flat.reshape(-1, int(block_size))
    scale = np.max(np.abs(blocks), axis=1).astype(np.float32)
    safe = np.maximum(scale, _SCALE_FLOOR)
    with np.errstate(invalid="ignore", over="ignore"):
        q = np.clip(np.round(blocks / safe[:, None] * qmax), -qmax, qmax)
    # int8-cast of NaN is undefined in C; force 0 — the non-finite SCALE
    # still poisons the block to NaN on dequantize
    q = np.where(np.isfinite(q), q, 0.0).astype(np.int8)
    return q, scale


def np_block_dequantize(q, scale, shape, dtype, bits=DEFAULT_BITS):
    qmax = _qmax(bits)
    safe = np.maximum(scale.astype(np.float32), _SCALE_FLOOR)
    with np.errstate(invalid="ignore"):
        blocks = q.astype(np.float32) * (safe / qmax)[:, None]
    size = int(np.prod(shape)) if len(shape) else 1
    return blocks.reshape(-1)[:size].reshape(shape).astype(dtype)


def encode_array(arr, mode="zlib", block_size=DEFAULT_BLOCK_SIZE,
                 bits=DEFAULT_BITS):
    """Encode one host array for the wire. Returns a dict holding the
    payload plus ``raw_bytes``/``wire_bytes`` accounting. ``mode``:

      "zlib"  lossless deflate of the raw bytes (bitwise round-trip —
              safe for params/optimizer state whose exactness guarantees
              must survive shipping)
      "q8"    the lossy block codec (float32/float64 arrays only; other
              dtypes fall back to zlib so integer counters and exotic
              dtypes always round-trip exactly)

    The returned dict carries the numpy dtype OBJECT (in-process use by
    the elastic state ship); it is not a serialization format — disk
    payloads go through io.save_checkpoint's npz layout instead."""
    arr = np.ascontiguousarray(arr)
    enc = {"shape": arr.shape, "dtype": arr.dtype,
           "raw_bytes": int(arr.nbytes)}
    if mode == "q8" and arr.dtype in (np.float32, np.float64):
        q, scale = np_block_quantize(arr, block_size, bits)
        enc.update(mode="q8", q=q, scale=scale, block_size=int(block_size),
                   bits=int(bits),
                   wire_bytes=int(q.nbytes + scale.nbytes))
        return enc
    if mode not in ("zlib", "q8"):
        raise ValueError("encode_array mode must be 'zlib' or 'q8', got %r"
                         % (mode,))
    payload = zlib.compress(arr.tobytes(), 1)
    enc.update(mode="zlib", data=payload, wire_bytes=int(len(payload)))
    return enc


def decode_array(enc):
    """Inverse of :func:`encode_array`."""
    if enc["mode"] == "q8":
        return np_block_dequantize(enc["q"], enc["scale"], enc["shape"],
                                   enc["dtype"], enc["bits"])
    raw = zlib.decompress(enc["data"])
    return np.frombuffer(raw, dtype=enc["dtype"]).reshape(
        enc["shape"]).copy()


@register_op("fake_channel_wise_quantize_dequantize_abs_max")
def _fake_qdq_channel(ctx, ins, attrs):
    """Per-output-channel abs-max sim-quant (reference
    fake_channel_wise_quantize_abs_max); channel = axis 0 for conv
    weights (OIHW), last axis for matmul weights via quant_axis."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    axis = int(attrs.get("quant_axis", 0))
    qmax = 2.0 ** (bits - 1) - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=red, keepdims=True), 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax - 1, qmax)
    qdq = q * scale / qmax
    out = x + jax.lax.stop_gradient(qdq - x)
    return {"Out": out, "OutScale": scale.reshape(-1)}
