"""Quantization op kernels.

Reference parity: paddle/fluid/operators/fake_quantize_op.cc + the
contrib/slim quantization passes. Simulated quantization: values are
quantized->dequantized in fp so XLA still runs bf16/fp32 matmuls; gradients
pass straight through (STE), expressed exactly as
x + stop_gradient(qdq(x) - x).
"""
import jax
import jax.numpy as jnp

from .registry import register_op


def _qdq_abs_max(x, bits, scale=None):
    qmax = 2.0 ** (bits - 1) - 1
    if scale is None:
        scale = jnp.max(jnp.abs(x))
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax - 1, qmax)
    return q * scale / qmax, scale


@register_op("fake_quantize_dequantize_abs_max")
def _fake_qdq_abs_max(ctx, ins, attrs):
    """Per-tensor abs-max sim-quant with STE gradient (reference
    fake_quantize_dequantize_abs_max op)."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    qdq, scale = _qdq_abs_max(x, bits)
    out = x + jax.lax.stop_gradient(qdq - x)
    return {"Out": out, "OutScale": scale[None]}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             nondiff=("InScale", "InState", "InAccum"))
def _fake_qdq_moving_avg(ctx, ins, attrs):
    """Moving-average abs-max sim-quant (reference
    fake_quantize_dequantize_moving_average_abs_max): scale tracks
    rate * state + abs_max running average; STE gradient."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    rate = float(attrs.get("moving_rate", 0.9))
    state = ins["InState"][0] if ins.get("InState") else jnp.ones((1,))
    accum = ins["InAccum"][0] if ins.get("InAccum") else jnp.zeros((1,))
    cur = jnp.max(jnp.abs(x))
    new_state = rate * state + 1.0
    new_accum = rate * accum + cur
    scale = new_accum / new_state
    qdq, _ = _qdq_abs_max(x, bits, scale.reshape(()))
    out = x + jax.lax.stop_gradient(qdq - x)
    return {"Out": out, "OutScale": scale.reshape(1),
            "OutState": new_state, "OutAccum": new_accum}


@register_op("fake_channel_wise_quantize_dequantize_abs_max")
def _fake_qdq_channel(ctx, ins, attrs):
    """Per-output-channel abs-max sim-quant (reference
    fake_channel_wise_quantize_abs_max); channel = axis 0 for conv
    weights (OIHW), last axis for matmul weights via quant_axis."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    axis = int(attrs.get("quant_axis", 0))
    qmax = 2.0 ** (bits - 1) - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=red, keepdims=True), 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax - 1, qmax)
    qdq = q * scale / qmax
    out = x + jax.lax.stop_gradient(qdq - x)
    return {"Out": out, "OutScale": scale.reshape(-1)}
