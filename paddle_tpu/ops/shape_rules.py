"""Static shape/dtype inference rules — the kernels' abstract twins.

Reference parity: each reference OpMaker registers an InferShape beside
its kernels (paddle/fluid/framework/op_desc.cc InferShapeContext); here
the rule set lives beside the JAX kernel registry and is consumed by
framework/analysis.py's shape pass. A rule computes output metadata from
input metadata WITHOUT tracing (no JAX import needed on the hot path)
and raises :class:`ShapeError` on a genuine violation.

Contract (the no-false-positive invariant):
  * metadata is a :class:`TensorMeta` — ``shape`` is a tuple whose
    entries may be None (unknown dim, e.g. the -1 batch dim) or None
    entirely (unknown rank); ``dtype`` is a canonical dtype string or
    None.
  * a rule must SKIP any check that needs an unknown dim/dtype and
    propagate unknowns instead; ops with no registered rule infer top
    (fully unknown) everywhere.
  * ``ShapeError(msg, severity=)`` carries "error" for certain
    violations (wrong matmul width, unbroadcastable add, reshape
    element mismatch) and "warning" for suspicious-but-runnable
    patterns (int/float elementwise mix, which jnp silently promotes).
"""
import math

from .registry import register_shape_rule

_FLOATS = ("float16", "bfloat16", "float32", "float64")
_INTS = ("int8", "uint8", "int16", "int32", "int64", "bool")


class TensorMeta(object):
    """Abstract (shape, dtype) of one value flowing through a Program."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape=None, dtype=None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    @property
    def rank(self):
        return None if self.shape is None else len(self.shape)

    def __repr__(self):
        return "TensorMeta(%s, %s)" % (self.shape, self.dtype)


def top():
    return TensorMeta(None, None)


class ShapeError(Exception):
    """A static shape/dtype violation (severity "error" | "warning")."""

    def __init__(self, message, severity="error"):
        super(ShapeError, self).__init__(message)
        self.severity = severity


def _x(ins, slot="X"):
    vals = ins.get(slot) or [top()]
    return vals[0]


def _known(shape):
    return shape is not None and all(d is not None for d in shape)


def _same_shape_out(op, ins, attrs, slot="X", out="Out"):
    m = _x(ins, slot)
    return {out: [TensorMeta(m.shape, m.dtype)]}


def _dtype_mix(a, b, what):
    """Flag dtype mixes. Warning severity, not error: the AMP path
    (contrib/mixed_precision) leans on jnp's weak promotion on purpose
    (bf16 matmul output + f32 master bias), so a mix is suspicious but
    runnable — strict mode must not refuse AMP programs."""
    if a is None or b is None or a == b:
        return
    if a in _FLOATS and b in _FLOATS:
        raise ShapeError(
            "%s mixes float dtypes %s and %s without a cast — jnp "
            "promotes silently; intentional under AMP, a wasted-"
            "bandwidth bug anywhere else" % (what, a, b),
            severity="warning")
    if (a in _FLOATS) != (b in _FLOATS):
        raise ShapeError(
            "%s mixes %s and %s — jnp weak promotion will pick a type "
            "silently; cast explicitly" % (what, a, b),
            severity="warning")


def _result_dtype(a, b):
    if a == b:
        return a
    return None


# ---------------------------------------------------------------------------
# elementwise family (fluid axis-broadcast semantics, math_ops._bcast)
# ---------------------------------------------------------------------------

def _fluid_broadcast(xs, ys, axis):
    """Mirror math_ops._bcast on abstract shapes; None dims match
    anything. Returns the result shape or raises ShapeError."""
    if xs is None or ys is None:
        return None
    if len(ys) > len(xs):
        return _fluid_broadcast(ys, xs, axis)
    if len(xs) != len(ys):
        if axis is None or axis == -1:
            axis = len(xs) - len(ys)
        if axis < 0 or axis + len(ys) > len(xs):
            raise ShapeError(
                "elementwise axis=%d cannot align a rank-%d operand "
                "into rank %d" % (axis, len(ys), len(xs)))
        ys = (1,) * axis + tuple(ys) + (1,) * (len(xs) - axis - len(ys))
    out = []
    for a, b in zip(xs, ys):
        if a is None or b is None:
            out.append(a if b == 1 else (b if a == 1 else None))
        elif a == b or b == 1:
            out.append(a)
        elif a == 1:
            out.append(b)
        else:
            raise ShapeError(
                "elementwise operands are not broadcastable: %s vs %s"
                % (tuple(xs), tuple(ys)))
    return tuple(out)


def _elementwise_rule(op, ins, attrs):
    x, y = _x(ins, "X"), _x(ins, "Y")
    _dtype_mix(x.dtype, y.dtype,
               "op {%s}" % op.type)
    shape = _fluid_broadcast(x.shape, y.shape, attrs.get("axis", -1))
    return {"Out": [TensorMeta(shape, _result_dtype(x.dtype, y.dtype))]}


for _t in ("elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "elementwise_max", "elementwise_min",
           "elementwise_pow", "elementwise_mod", "elementwise_floordiv"):
    register_shape_rule(_t)(_elementwise_rule)


@register_shape_rule("maximum", "minimum")
def _binop_nobcast(op, ins, attrs):
    x, y = _x(ins, "X"), _x(ins, "Y")
    _dtype_mix(x.dtype, y.dtype, "op {%s}" % op.type)
    shape = _fluid_broadcast(x.shape, y.shape, -1)
    return {"Out": [TensorMeta(shape, _result_dtype(x.dtype, y.dtype))]}


@register_shape_rule("sum")
def _sum_rule(op, ins, attrs):
    metas = ins.get("X") or [top()]
    shape, dtype = metas[0].shape, metas[0].dtype
    for m in metas[1:]:
        shape = _fluid_broadcast(shape, m.shape, -1)
        if dtype != m.dtype:
            dtype = None
    return {"Out": [TensorMeta(shape, dtype)]}


# ---------------------------------------------------------------------------
# shape-preserving unary ops (activations + friends)
# ---------------------------------------------------------------------------

def _register_unary():
    from .math_ops import _ACTIVATIONS
    unary = set(_ACTIVATIONS) | {
        "scale", "clip", "pow", "logical_not", "isnan", "isinf",
        "clip_by_norm", "increment", "assign", "fill_any_like",
        "fill_zeros_like", "softmax", "log_softmax", "label_smooth",
        "l2_normalize", "add_position_encoding",
    }
    for t in sorted(unary):
        register_shape_rule(t)(_same_shape_out)


_register_unary()


@register_shape_rule("cumsum")
def _cumsum_rule(op, ins, attrs):
    m = _x(ins)
    if attrs.get("flatten", False):
        n = math.prod(m.shape) if m.shape is not None and _known(m.shape) \
            else None
        return {"Out": [TensorMeta((n,), m.dtype)]}
    return {"Out": [TensorMeta(m.shape, m.dtype)]}


@register_shape_rule("dropout")
def _dropout_rule(op, ins, attrs):
    m = _x(ins)
    return {"Out": [TensorMeta(m.shape, m.dtype)],
            "Mask": [TensorMeta(m.shape, "uint8")]}


@register_shape_rule("cast")
def _cast_rule(op, ins, attrs):
    from ..framework.dtypes import normalize_dtype
    m = _x(ins)
    dt = attrs.get("out_dtype")
    try:
        dt = normalize_dtype(dt) if dt is not None else None
    except Exception:
        dt = None
    return {"Out": [TensorMeta(m.shape, dt)]}


@register_shape_rule("mean", "isfinite")
def _scalar_rule(op, ins, attrs):
    m = _x(ins)
    dt = "bool" if op.type == "isfinite" else m.dtype
    return {"Out": [TensorMeta((1,), dt)]}


@register_shape_rule("squared_l2_norm")
def _sq_l2_rule(op, ins, attrs):
    # the kernel reshapes to rank 0 (reshape(())), not (1,)
    return {"Out": [TensorMeta((), _x(ins).dtype)]}


# ---------------------------------------------------------------------------
# matmul / mul — the MXU family (wrong-width heads die here)
# ---------------------------------------------------------------------------

@register_shape_rule("matmul")
def _matmul_rule(op, ins, attrs):
    x, y = _x(ins, "X"), _x(ins, "Y")
    _dtype_mix(x.dtype, y.dtype, "op {matmul}")
    xs, ys = x.shape, y.shape
    if xs is not None and len(xs) == 1:
        xs = (1,) + tuple(xs)
    if ys is not None and len(ys) == 1:
        ys = tuple(ys) + (1,)
    if attrs.get("transpose_X", False) and xs is not None and len(xs) >= 2:
        xs = xs[:-2] + (xs[-1], xs[-2])
    if attrs.get("transpose_Y", False) and ys is not None and len(ys) >= 2:
        ys = ys[:-2] + (ys[-1], ys[-2])
    out_dt = attrs.get("out_dtype")
    if out_dt:
        from ..framework.dtypes import normalize_dtype
        try:
            dtype = normalize_dtype(out_dt)
        except Exception:
            dtype = None
    else:
        dtype = _result_dtype(x.dtype, y.dtype)
    if xs is None or ys is None or len(xs) < 2 or len(ys) < 2:
        return {"Out": [TensorMeta(None, dtype)]}
    k1, k2 = xs[-1], ys[-2]
    if k1 is not None and k2 is not None and k1 != k2:
        raise ShapeError(
            "matmul contraction width mismatch: X%s @ Y%s contracts "
            "%d against %d (after transpose flags)"
            % (tuple(xs), tuple(ys), k1, k2))
    # batch dims broadcast numpy-style
    batch = _fluid_broadcast(xs[:-2], ys[:-2], -1) \
        if (xs[:-2] or ys[:-2]) else ()
    return {"Out": [TensorMeta(tuple(batch or ()) + (xs[-2], ys[-1]),
                               dtype)]}


@register_shape_rule("mul")
def _mul_rule(op, ins, attrs):
    x, y = _x(ins, "X"), _x(ins, "Y")
    _dtype_mix(x.dtype, y.dtype, "op {mul}")
    xs, ys = x.shape, y.shape
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    if xs is None or ys is None:
        return {"Out": [top()]}
    if len(xs) < xn + 1 or len(ys) < yn + 1:
        return {"Out": [top()]}
    kx = xs[xn:]
    ky = ys[:yn]
    if _known(kx) and _known(ky) and math.prod(kx) != math.prod(ky):
        raise ShapeError(
            "mul contraction width mismatch: X%s x_num_col_dims=%d "
            "flattens to %d columns but Y%s y_num_col_dims=%d provides "
            "%d rows" % (tuple(xs), xn, math.prod(kx), tuple(ys), yn,
                         math.prod(ky)))
    return {"Out": [TensorMeta(tuple(xs[:xn]) + tuple(ys[yn:]),
                               _result_dtype(x.dtype, y.dtype))]}


@register_shape_rule("dot")
def _dot_rule(op, ins, attrs):
    x, y = _x(ins, "X"), _x(ins, "Y")
    _dtype_mix(x.dtype, y.dtype, "op {dot}")
    shape = _fluid_broadcast(x.shape, y.shape, -1)
    if shape is not None and len(shape) >= 1:
        shape = tuple(shape[:-1]) + (1,)
    return {"Out": [TensorMeta(shape, _result_dtype(x.dtype, y.dtype))]}


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduce_rule(op, ins, attrs):
    m = _x(ins)
    dtype = "bool" if op.type in ("reduce_all", "reduce_any") else m.dtype
    if m.shape is None:
        return {"Out": [TensorMeta(None, dtype)]}
    dims = attrs.get("dim", [0])
    reduce_all = attrs.get("reduce_all", False) or dims is None
    keep = attrs.get("keep_dim", False)
    rank = len(m.shape)
    if reduce_all:
        shape = (1,) * rank if keep else (1,)
        return {"Out": [TensorMeta(shape, dtype)]}
    if not isinstance(dims, (list, tuple)):
        dims = [dims]
    try:
        axes = {d % rank for d in dims}
    except (TypeError, ZeroDivisionError):
        return {"Out": [TensorMeta(None, dtype)]}
    for d in dims:
        if not -rank <= d < rank:
            raise ShapeError(
                "reduce dim %d out of range for rank-%d input %s"
                % (d, rank, m.shape))
    shape = tuple(1 if i in axes else d for i, d in enumerate(m.shape)) \
        if keep else tuple(d for i, d in enumerate(m.shape)
                           if i not in axes)
    return {"Out": [TensorMeta(shape, dtype)]}


for _t in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
           "reduce_prod", "reduce_all", "reduce_any"):
    register_shape_rule(_t)(_reduce_rule)


# ---------------------------------------------------------------------------
# reshape / layout family
# ---------------------------------------------------------------------------

@register_shape_rule("reshape2")
def _reshape2_rule(op, ins, attrs):
    m = _x(ins)
    want = list(attrs.get("shape") or [])
    if not want:
        return {"Out": [TensorMeta(None, m.dtype)]}
    out = []
    for i, s in enumerate(want):
        if s == 0:
            if m.shape is not None and i < len(m.shape):
                out.append(m.shape[i])
            else:
                out.append(None)
        elif s == -1:
            out.append(-1)
        else:
            out.append(int(s))
    n_infer = sum(1 for d in out if d == -1)
    if n_infer > 1:
        raise ShapeError("reshape2 shape %r has more than one -1" % want)
    if m.shape is not None and _known(m.shape):
        total = math.prod(m.shape) if m.shape else 1
        fixed = [d for d in out if d not in (-1, None)]
        if None not in out:
            prod = math.prod(fixed) if fixed else 1
            if n_infer:
                if prod == 0 or total % prod != 0:
                    raise ShapeError(
                        "reshape2 cannot infer -1: input %s (%d elements) "
                        "does not divide by %r" % (m.shape, total, want))
                out[out.index(-1)] = total // prod
            elif prod != total:
                raise ShapeError(
                    "reshape2 element count mismatch: input %s has %d "
                    "elements, target %r has %d"
                    % (m.shape, total, want, prod))
    out = [None if d == -1 else d for d in out]
    return {"Out": [TensorMeta(tuple(out), m.dtype)]}


@register_shape_rule("transpose2")
def _transpose2_rule(op, ins, attrs):
    m = _x(ins)
    perm = attrs.get("axis")
    if m.shape is None or perm is None:
        return {"Out": [TensorMeta(None, m.dtype)]}
    if sorted(a % len(m.shape) if -len(m.shape) <= a < len(m.shape)
              else -1 for a in perm) != list(range(len(m.shape))):
        raise ShapeError(
            "transpose2 axis %r is not a permutation of rank %d"
            % (perm, len(m.shape)))
    return {"Out": [TensorMeta(tuple(m.shape[a] for a in perm),
                               m.dtype)]}


@register_shape_rule("flatten2")
def _flatten2_rule(op, ins, attrs):
    m = _x(ins)
    axis = attrs.get("axis", 1)
    if m.shape is None or not _known(m.shape):
        return {"Out": [TensorMeta(None, m.dtype)]}
    lead = math.prod(m.shape[:axis]) if axis else 1
    rest = math.prod(m.shape[axis:]) if m.shape[axis:] else 1
    return {"Out": [TensorMeta((lead, rest), m.dtype)]}


@register_shape_rule("concat")
def _concat_rule(op, ins, attrs):
    metas = ins.get("X") or [top()]
    axis = attrs.get("axis", 0)
    shapes = [m.shape for m in metas]
    if any(s is None for s in shapes):
        return {"Out": [TensorMeta(None, metas[0].dtype)]}
    rank = len(shapes[0])
    if any(len(s) != rank for s in shapes):
        raise ShapeError("concat operands have mixed ranks: %r" % (shapes,))
    ax = axis % rank if rank else 0
    out = []
    for i in range(rank):
        dims = [s[i] for s in shapes]
        if i == ax:
            out.append(None if any(d is None for d in dims)
                       else sum(dims))
        else:
            known = {d for d in dims if d is not None}
            if len(known) > 1:
                raise ShapeError(
                    "concat operands disagree on non-concat dim %d: %r"
                    % (i, shapes))
            out.append(known.pop() if known else None)
    dtype = metas[0].dtype
    if any(m.dtype != dtype for m in metas):
        dtype = None
    return {"Out": [TensorMeta(tuple(out), dtype)]}


@register_shape_rule("stack")
def _stack_rule(op, ins, attrs):
    metas = ins.get("X") or [top()]
    axis = attrs.get("axis", 0)
    s = metas[0].shape
    if s is None:
        return {"Y": [TensorMeta(None, metas[0].dtype)]}
    ax = axis % (len(s) + 1)
    return {"Y": [TensorMeta(tuple(s[:ax]) + (len(metas),)
                             + tuple(s[ax:]), metas[0].dtype)]}


@register_shape_rule("squeeze2")
def _squeeze2_rule(op, ins, attrs):
    m = _x(ins)
    axes = attrs.get("axes", [])
    if m.shape is None:
        return {"Out": [TensorMeta(None, m.dtype)]}
    rank = len(m.shape)
    if not axes:
        shape = tuple(d for d in m.shape if d != 1)
    else:
        drop = {a % rank for a in axes
                if m.shape[a % rank] == 1}
        shape = tuple(d for i, d in enumerate(m.shape) if i not in drop)
    return {"Out": [TensorMeta(shape, m.dtype)]}


@register_shape_rule("unsqueeze2")
def _unsqueeze2_rule(op, ins, attrs):
    m = _x(ins)
    if m.shape is None:
        return {"Out": [TensorMeta(None, m.dtype)]}
    shape = list(m.shape)
    for a in sorted(attrs.get("axes", [])):
        if not -len(shape) - 1 <= a <= len(shape):
            raise ShapeError(
                "unsqueeze2 axis %d out of range for rank %d"
                % (a, len(shape)))
        shape.insert(a if a >= 0 else a + len(shape) + 1, 1)
    return {"Out": [TensorMeta(tuple(shape), m.dtype)]}


# ---------------------------------------------------------------------------
# fills / constants
# ---------------------------------------------------------------------------

@register_shape_rule("fill_constant")
def _fill_constant_rule(op, ins, attrs):
    from ..framework.dtypes import normalize_dtype
    shape = attrs.get("shape")
    try:
        dt = normalize_dtype(attrs.get("dtype", "float32"))
    except Exception:
        dt = None
    return {"Out": [TensorMeta(tuple(shape) if shape else None, dt)]}


@register_shape_rule("fill_constant_batch_size_like")
def _fill_bsl_rule(op, ins, attrs):
    from ..framework.dtypes import normalize_dtype
    ref = _x(ins, "Input")
    shape = list(attrs.get("shape") or [])
    if not shape:
        return {"Out": [top()]}
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    if ref.shape is not None and in_idx < len(ref.shape) \
            and out_idx < len(shape):
        shape[out_idx] = ref.shape[in_idx]
    shape = [None if d in (-1,) else d for d in shape]
    try:
        dt = normalize_dtype(attrs.get("dtype", "float32"))
    except Exception:
        dt = None
    return {"Out": [TensorMeta(tuple(shape), dt)]}


# ---------------------------------------------------------------------------
# embedding / one-hot
# ---------------------------------------------------------------------------

@register_shape_rule("lookup_table", "lookup_table_v2")
def _lookup_rule(op, ins, attrs):
    w, ids = _x(ins, "W"), _x(ins, "Ids")
    if ids.dtype is not None and ids.dtype in _FLOATS:
        raise ShapeError(
            "lookup_table Ids must be integer, got %s" % ids.dtype)
    if w.shape is None or len(w.shape) != 2 or ids.shape is None:
        return {"Out": [TensorMeta(None, w.dtype)]}
    ids_shape = ids.shape
    if len(ids_shape) >= 2 and ids_shape[-1] == 1:
        ids_shape = ids_shape[:-1]
    return {"Out": [TensorMeta(tuple(ids_shape) + (w.shape[1],),
                               w.dtype)]}


@register_shape_rule("one_hot")
def _one_hot_rule(op, ins, attrs):
    from ..framework.dtypes import normalize_dtype
    m = _x(ins)
    depth = attrs.get("depth")
    try:
        dt = normalize_dtype(attrs.get("dtype", "float32"))
    except Exception:
        dt = None
    if m.shape is None or depth is None:
        return {"Out": [TensorMeta(None, dt)]}
    shape = m.shape
    if len(shape) >= 2 and shape[-1] == 1:
        shape = shape[:-1]
    return {"Out": [TensorMeta(tuple(shape) + (int(depth),), dt)]}


# ---------------------------------------------------------------------------
# losses / heads — the CE family
# ---------------------------------------------------------------------------

def _ce_label_check(logits, label, op_type, soft, axis=-1):
    """Shared logits-vs-label structural check. Returns the per-example
    loss shape (label-aligned + trailing 1) or None when unknown."""
    if logits.shape is None or label.shape is None:
        return None
    ls = tuple(logits.shape)
    if axis not in (-1, len(ls) - 1):
        return None
    if soft:
        if len(label.shape) != len(ls):
            raise ShapeError(
                "op {%s} soft_label=True needs Label rank %d == Logits "
                "rank, got %s vs %s" % (op_type, len(ls), label.shape, ls))
        c1, c2 = ls[-1], label.shape[-1]
        if c1 is not None and c2 is not None and c1 != c2:
            raise ShapeError(
                "op {%s} soft Label width %d != class width %d of the "
                "logits %s — a wrong-width head" % (op_type, c2, c1, ls))
        return tuple(label.shape[:-1]) + (1,)
    lbl = tuple(label.shape)
    if len(lbl) == len(ls) and lbl[-1] == 1:
        lbl = lbl[:-1]
    if len(lbl) != len(ls) - 1:
        raise ShapeError(
            "op {%s} hard Label %s does not align with Logits %s "
            "(want the logits shape minus the class dim, optionally "
            "with a trailing 1)" % (op_type, label.shape, ls))
    for a, b in zip(lbl, ls[:-1]):
        if a is not None and b is not None and a != b:
            raise ShapeError(
                "op {%s} Label dims %s disagree with Logits dims %s"
                % (op_type, label.shape, ls))
    return tuple(lbl) + (1,)


@register_shape_rule("softmax_with_cross_entropy")
def _swce_rule(op, ins, attrs):
    logits, label = _x(ins, "Logits"), _x(ins, "Label")
    loss_shape = _ce_label_check(logits, label, op.type,
                                 attrs.get("soft_label", False),
                                 attrs.get("axis", -1))
    return {"Softmax": [TensorMeta(logits.shape, logits.dtype)],
            "Loss": [TensorMeta(loss_shape, logits.dtype)]}


@register_shape_rule("cross_entropy")
def _ce_rule(op, ins, attrs):
    x, label = _x(ins, "X"), _x(ins, "Label")
    loss_shape = _ce_label_check(x, label, op.type,
                                 attrs.get("soft_label", False))
    return {"Y": [TensorMeta(loss_shape, x.dtype)]}


@register_shape_rule("fused_mlm_head_loss")
def _mlm_head_rule(op, ins, attrs):
    hidden, weight = _x(ins, "Hidden"), _x(ins, "Weight")
    label = _x(ins, "Label")
    if hidden.shape is not None and weight.shape is not None \
            and len(hidden.shape) == 2 and len(weight.shape) == 2:
        d1, d2 = hidden.shape[-1], weight.shape[-1]
        if d1 is not None and d2 is not None and d1 != d2:
            raise ShapeError(
                "fused_mlm_head_loss Hidden width %d != Weight (V, D) "
                "width %d — a wrong-width head" % (d1, d2))
    t = hidden.shape[0] if hidden.shape is not None \
        and len(hidden.shape) >= 1 else None
    if label.shape is not None and _known(label.shape) and t is not None:
        lt = label.shape[0]
        if lt != t:
            raise ShapeError(
                "fused_mlm_head_loss Label rows %d != Hidden rows %s"
                % (lt, t))
    return {"Loss": [TensorMeta((t, 1), "float32")]}


@register_shape_rule("scaled_dot_product_attention")
def _sdpa_rule(op, ins, attrs):
    q, k, v = _x(ins, "Q"), _x(ins, "K"), _x(ins, "V")
    for name, m in (("Q", q), ("K", k), ("V", v)):
        if m.shape is not None and len(m.shape) < 2:
            raise ShapeError(
                "scaled_dot_product_attention %s needs rank >= 2, got %s"
                % (name, m.shape))
    if q.shape is None or k.shape is None or v.shape is None:
        return {"Out": [TensorMeta(None, q.dtype)]}
    dq, dk = q.shape[-1], k.shape[-1]
    if dq is not None and dk is not None and dq != dk:
        raise ShapeError(
            "scaled_dot_product_attention head width mismatch: Q%s vs "
            "K%s contract %d against %d" % (q.shape, k.shape, dq, dk))
    sk, sv = k.shape[-2], v.shape[-2]
    if sk is not None and sv is not None and sk != sv:
        raise ShapeError(
            "scaled_dot_product_attention K rows %d != V rows %d"
            % (sk, sv))
    return {"Out": [TensorMeta(tuple(q.shape[:-1]) + (v.shape[-1],),
                               q.dtype)]}


@register_shape_rule("layer_norm")
def _layer_norm_rule(op, ins, attrs):
    m = _x(ins)
    begin = attrs.get("begin_norm_axis", 1)
    mean_shape = None
    if m.shape is not None and 0 <= begin <= len(m.shape):
        mean_shape = tuple(m.shape[:begin])
    return {"Y": [TensorMeta(m.shape, m.dtype)],
            "Mean": [TensorMeta(mean_shape, "float32")],
            "Variance": [TensorMeta(mean_shape, "float32")]}


@register_shape_rule("batch_norm")
def _batch_norm_rule(op, ins, attrs):
    m = _x(ins)
    return {"Y": [TensorMeta(m.shape, m.dtype)]}
