"""Misc layer-tail kernels: multiplex, crop, cos_sim, bilinear tensor
product, unique, mean_iou, chunk_eval, data_norm, spectral_norm.

Reference parity: paddle/fluid/operators/{multiplex_op, crop_op,
cos_sim_op, bilinear_tensor_product_op, unique_op, mean_iou_op,
chunk_eval_op, data_norm_op, spectral_norm_op}. Reference kernels are
Eigen/CUDA loops; these are vectorized jnp/lax programs (the chunk_eval
segment extraction becomes a cummax scan; unique becomes a static-shape
jnp.unique with a valid-count output since XLA has no dynamic shapes).
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
import numpy as np


@register_op("multiplex", nondiff=("Ids",))
def _multiplex(ctx, ins, attrs):
    """out[i] = inputs[index[i]][i] (ref multiplex_op.h row gather)."""
    xs = jnp.stack(ins["X"], axis=0)          # (K, N, D...)
    ids = ins["Ids"][0].reshape(-1).astype(jnp.int32)   # (N,)
    rows = jnp.arange(xs.shape[1])
    return {"Out": xs[ids, rows]}


@register_op("crop", nondiff=("Y", "Offsets"))
def _crop(ctx, ins, attrs):
    """Static crop (ref crop_op.h): slice `shape` at `offsets`."""
    x = ins["X"][0]
    shape = attrs.get("shape")
    if shape is None and ins.get("Y"):
        shape = list(ins["Y"][0].shape)
    offsets = attrs.get("offsets") or [0] * x.ndim
    idx = tuple(slice(int(o), int(o) + int(s))
                for o, s in zip(offsets, shape))
    return {"Out": x[idx]}


@register_op("cos_sim")
def _cos_sim(ctx, ins, attrs):
    """Ref cos_sim_op.h: per-row cosine; Y may be (1, D) broadcast."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=1, keepdims=True))
    num = jnp.sum(x * y, axis=1, keepdims=True)
    out = num / jnp.maximum(xn * yn, 1e-12)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ctx, ins, attrs):
    """out[b, i] = x[b] @ W[i] @ y[b] (+ bias) — one MXU einsum
    (ref bilinear_tensor_product_op.h loops over i)."""
    x, w, y = ins["X"][0], ins["Weight"][0], ins["Y"][0]
    out = jnp.einsum("bm,imn,bn->bi", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": out}


def _n_unique(x):
    """Number of distinct values: adjacent-difference count on sort(x)
    (jnp.unique's pad slots repeat the minimum, so counting transitions
    on its padded output overcounts)."""
    s = jnp.sort(x)
    return (1 + jnp.sum(s[1:] != s[:-1])).astype(jnp.int32)


@register_op("unique", nondiff=("X",), differentiable=False)
def _unique(ctx, ins, attrs):
    """Ref unique_op.h returns a dynamically-sized unique list; XLA needs
    static shapes, so Out is padded to len(X) (pad slots repeat the last
    unique value) and the valid length is returned in Count — the
    documented TPU-native deviation."""
    x = ins["X"][0].reshape(-1)
    uniq, index = jnp.unique(x, return_inverse=True, size=x.shape[0],
                             fill_value=None)
    return {"Out": uniq,
            "Index": index.astype(jnp.int32).reshape(ins["X"][0].shape),
            "Count": _n_unique(x)}


@register_op("unique_with_counts", nondiff=("X",), differentiable=False)
def _unique_with_counts(ctx, ins, attrs):
    x = ins["X"][0].reshape(-1)
    uniq, index, counts = jnp.unique(
        x, return_inverse=True, return_counts=True, size=x.shape[0],
        fill_value=None)
    return {"Out": uniq, "Index": index.astype(jnp.int32),
            "Counts": counts.astype(jnp.int32),
            "Count": _n_unique(x)}


@register_op("mean_iou", nondiff=("Predictions", "Labels"),
             differentiable=False)
def _mean_iou(ctx, ins, attrs):
    """Ref mean_iou_op.h: per-class IoU from confusion counts, averaged
    over classes that appear."""
    pred = ins["Predictions"][0].reshape(-1).astype(jnp.int32)
    label = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    nc = int(attrs["num_classes"])
    oh_p = jax.nn.one_hot(pred, nc, dtype=jnp.float32)
    oh_l = jax.nn.one_hot(label, nc, dtype=jnp.float32)
    inter = jnp.sum(oh_p * oh_l, axis=0)          # diag of confusion
    np_ = jnp.sum(oh_p, axis=0)
    nl = jnp.sum(oh_l, axis=0)
    union = np_ + nl - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
    denom = jnp.maximum(jnp.sum(present.astype(jnp.float32)), 1.0)
    return {"OutMeanIou": jnp.sum(iou) / denom,
            "OutWrong": (np_ + nl - 2 * inter).astype(jnp.int32),
            "OutCorrect": inter.astype(jnp.int32)}


# ---------------------------------------------------------------------------
# chunk_eval — vectorized segment extraction (ref chunk_eval_op.h
# GetSegments loop becomes boolean begin/end masks + a cummax over start
# positions; a chunk matches iff both sequences end a chunk at the same
# position with the same start and type)
# ---------------------------------------------------------------------------

_SCHEMES = {
    # scheme: (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_begin_end(tag, typ, ntt, tb, ti, te, ts, other, seq_mask):
    """begin[i]: position i starts a chunk; end[i]: i is a chunk's last
    position. Mirrors ChunkBegin/ChunkEnd in chunk_eval_op.h."""
    prev_tag = jnp.concatenate(
        [jnp.full_like(tag[:, :1], -1), tag[:, :-1]], axis=1)
    prev_typ = jnp.concatenate(
        [jnp.full_like(typ[:, :1], other), typ[:, :-1]], axis=1)

    def begins(ptag, ptyp, t, ty):
        in_other = ty == other
        p_other = ptyp == other
        diff_type = ty != ptyp
        tag_rule = ((t == tb) |
                    ((t == ti) & ((ptag == te) | (ptag == ts))) |
                    ((t == te) & ((ptag == te) | (ptag == ts))) |
                    (t == ts))
        return jnp.where(p_other, ~in_other,
                         jnp.where(in_other, False,
                                   jnp.where(diff_type, True, tag_rule)))

    def ends(ptag, ptyp, t, ty):
        # chunk containing position i-1 ends before i
        p_other = ptyp == other
        in_other = ty == other
        diff_type = ty != ptyp
        tag_rule = (((ptag == tb) & ((t == tb) | (t == ts))) |
                    ((ptag == ti) & ((t == tb) | (t == ts))) |
                    (ptag == te) | (ptag == ts))
        return jnp.where(p_other, False,
                         jnp.where(in_other, True,
                                   jnp.where(diff_type, True, tag_rule)))

    begin = begins(prev_tag, prev_typ, tag, typ) & seq_mask
    # end[i] from the transition i -> i+1 (or sequence end)
    next_tag = jnp.concatenate(
        [tag[:, 1:], jnp.full_like(tag[:, :1], -1)], axis=1)
    next_typ = jnp.concatenate(
        [typ[:, 1:], jnp.full_like(typ[:, :1], other)], axis=1)
    last = jnp.concatenate(
        [seq_mask[:, 1:] == False, jnp.ones_like(seq_mask[:, :1])],  # noqa
        axis=1) & seq_mask
    in_chunk = (typ != other) & seq_mask
    end = in_chunk & (last | ends(tag, typ, next_tag, next_typ))
    return begin & in_chunk, end


@register_op("chunk_eval", nondiff=("Inference", "Label", "SeqLength"),
             differentiable=False)
def _chunk_eval(ctx, ins, attrs):
    inf = ins["Inference"][0]
    lab = ins["Label"][0]
    if inf.ndim > 2:
        inf = inf.reshape(inf.shape[0], -1)
        lab = lab.reshape(lab.shape[0], -1)
    b, t = inf.shape
    if ins.get("SeqLength"):
        seq_len = ins["SeqLength"][0].reshape(-1)
        seq_mask = jnp.arange(t)[None, :] < seq_len[:, None]
    else:
        seq_mask = jnp.ones((b, t), bool)
    scheme = attrs.get("chunk_scheme", "IOB")
    ntt, tb, ti, te, ts = _SCHEMES[scheme]
    other = int(attrs["num_chunk_types"])
    excluded = attrs.get("excluded_chunk_types") or []

    def seg(x):
        x = x.astype(jnp.int32)
        tag = x % ntt
        typ = x // ntt
        begin, end = _chunk_begin_end(tag, typ, ntt, tb, ti, te, ts,
                                      other, seq_mask)
        # start position of the chunk containing i (valid at end positions)
        pos = jnp.arange(t, dtype=jnp.int32)[None, :]
        sidx = lax.cummax(jnp.where(begin, pos, -1), axis=1)
        keep = jnp.ones_like(begin)
        for e in excluded:
            keep = keep & (typ != int(e))
        return begin & keep, end & keep, sidx, typ

    b_i, e_i, s_i, ty_i = seg(inf)
    b_l, e_l, s_l, ty_l = seg(lab)
    num_inf = jnp.sum(b_i)
    num_lab = jnp.sum(b_l)
    correct = jnp.sum(e_i & e_l & (s_i == s_l) & (ty_i == ty_l))
    p = jnp.where(num_inf > 0, correct / jnp.maximum(num_inf, 1), 0.0)
    r = jnp.where(num_lab > 0, correct / jnp.maximum(num_lab, 1), 0.0)
    f1 = jnp.where(correct > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)
    one = lambda v, dt: jnp.asarray(v, dt).reshape(1)  # noqa: E731
    return {"Precision": one(p, jnp.float32),
            "Recall": one(r, jnp.float32),
            "F1-Score": one(f1, jnp.float32),
            "NumInferChunks": one(num_inf, jnp.int32),
            "NumLabelChunks": one(num_lab, jnp.int32),
            "NumCorrectChunks": one(correct, jnp.int32)}


# ---------------------------------------------------------------------------
# data_norm / spectral_norm
# ---------------------------------------------------------------------------

@register_op("data_norm", nondiff=("BatchSize", "BatchSum", "BatchSquareSum"))
def _data_norm(ctx, ins, attrs):
    """Ref data_norm_op.cc: means = batch_sum / batch_size, scales =
    sqrt(batch_size / batch_square_sum), y = (x - means) * scales. The
    reference accumulates the running stats in its grad kernel; here the
    forward emits the updated accumulators (batch_norm-style outputs)."""
    x = ins["X"][0]                        # (N, C)
    bsize = ins["BatchSize"][0]
    bsum = ins["BatchSum"][0]
    bsq = ins["BatchSquareSum"][0]
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    y = (x - means[None, :]) * scales[None, :]
    n = x.shape[0]
    new_size = bsize + n
    new_sum = bsum + jnp.sum(x, axis=0)
    new_sq = bsq + jnp.sum(jnp.square(x - means[None, :]), axis=0)
    return {"Y": y, "Means": means, "Scales": scales,
            "BatchSizeOut": lax.stop_gradient(new_size),
            "BatchSumOut": lax.stop_gradient(new_sum),
            "BatchSquareSumOut": lax.stop_gradient(new_sq)}


@register_op("spectral_norm", nondiff=("U", "V"))
def _spectral_norm(ctx, ins, attrs):
    """Ref spectral_norm_op.h: power iteration on W reshaped to (h, w)
    with dim moved first; weight_out = W / sigma. U/V iterates are
    treated as constants (stop_gradient), exactly like the reference."""
    w = ins["Weight"][0]
    u = ins["U"][0]                        # (h,)
    v = ins["V"][0]                        # (w,)
    dim = int(attrs.get("dim", 0))
    power_iters = int(attrs.get("power_iters", 1))
    eps = float(attrs.get("eps", 1e-12))
    perm = [dim] + [i for i in range(w.ndim) if i != dim]
    wm = jnp.transpose(w, perm)
    h = wm.shape[0]
    wmat = wm.reshape(h, -1)

    def l2n(a):
        return a / jnp.maximum(jnp.linalg.norm(a), eps)

    for _ in range(power_iters):
        v = l2n(wmat.T @ u)
        u = l2n(wmat @ v)
    u = lax.stop_gradient(u)
    v = lax.stop_gradient(v)
    sigma = u @ (wmat @ v)
    out = wmat / sigma
    inv = [perm.index(i) for i in range(w.ndim)]
    out = jnp.transpose(out.reshape(wm.shape), inv)
    return {"Out": out, "UOut": u, "VOut": v}


# ---------------------------------------------------------------------------
# py_func — host-side escape hatch (reference python/paddle/fluid/layers/
# nn.py:12369 py_func + operators/py_func_op.cc). TPU-native mapping:
# jax.pure_callback embeds the host call in the jitted step; a registered
# backward_func becomes a custom vjp whose rule is itself a callback.
# ---------------------------------------------------------------------------

_PY_FUNC_REGISTRY = {}


def register_py_func(func, backward_func=None):
    fid = len(_PY_FUNC_REGISTRY)
    _PY_FUNC_REGISTRY[fid] = (func, backward_func)
    return fid


def _np_results(res, metas):
    if not isinstance(res, (list, tuple)):
        res = [res]
    if len(res) != len(metas):
        raise ValueError("py_func returned %d values, declared %d outputs"
                         % (len(res), len(metas)))
    return [np.asarray(r, dtype=m.dtype).reshape(m.shape)
            for r, m in zip(res, metas)]


@register_op("py_func")
def _py_func(ctx, ins, attrs):
    import jax
    func, bwd = _PY_FUNC_REGISTRY[attrs["func_id"]]
    out_meta = [jax.ShapeDtypeStruct(tuple(s), _dt(d))
                for s, d in attrs["out_meta"]]
    xs = tuple(ins["X"])

    def call(*arrays):
        return _np_results(func(*[np.asarray(a) for a in arrays]), out_meta)

    if bwd is None:
        outs = jax.pure_callback(call, out_meta, *xs)
        # no registered backward: explicit stop_gradient, like the
        # reference's non-differentiable py_func default
        return {"Out": [jax.lax.stop_gradient(o) for o in outs]}

    in_meta = [jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs]
    # integer primals take float0 cotangents (jax.custom_vjp contract);
    # the callback only carries grads for the inexact inputs
    diff_idx = [i for i, x in enumerate(xs)
                if jnp.issubdtype(x.dtype, jnp.inexact)]
    diff_meta = [in_meta[i] for i in diff_idx]

    @jax.custom_vjp
    def fwd_fn(*xs):
        return tuple(jax.pure_callback(call, out_meta, *xs))

    def fwd(*xs):
        outs = fwd_fn(*xs)
        return outs, (xs, outs)

    def bwd_rule(res, gouts):
        xs, outs = res

        def bcall(*arrays):
            arrays = [np.asarray(a) for a in arrays]
            n, m = len(xs), len(outs)
            # contract: backward_func(*inputs, *outputs, *out_grads)
            # -> per-input grads (None allowed -> zeros)
            gs = bwd(*arrays[:n], *arrays[n:n + m], *arrays[n + m:])
            if not isinstance(gs, (list, tuple)):
                gs = [gs]
            return [np.zeros(in_meta[i].shape, in_meta[i].dtype)
                    if gs[i] is None
                    else np.asarray(gs[i], dtype=in_meta[i].dtype)
                    .reshape(in_meta[i].shape)
                    for i in diff_idx]

        gdiff = jax.pure_callback(bcall, diff_meta, *xs, *outs, *gouts)
        gdiff = list(gdiff) if isinstance(gdiff, (list, tuple)) else [gdiff]
        gins = []
        for i, x in enumerate(xs):
            if i in diff_idx:
                gins.append(gdiff[diff_idx.index(i)])
            else:
                gins.append(np.zeros(x.shape, jax.dtypes.float0))
        return tuple(gins)

    fwd_fn.defvjp(fwd, bwd_rule)
    return {"Out": list(fwd_fn(*xs))}


def _dt(name):
    from ..framework.dtypes import to_jax_dtype
    return to_jax_dtype(name)
