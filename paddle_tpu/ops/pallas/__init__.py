"""Pallas TPU kernel library.

Hand-fused kernels for the per-step hot path, each behind the oracle
pattern: a pure-JAX reference in tests, interpret-mode execution on CPU
(tier-1 exercises the real kernel logic), XLA fallback when shapes
don't tile, and — for the registry-wired ops — trace-time dispatch via
``BuildStrategy.use_pallas`` + the ``ops.pallas_dispatch`` scope.

  flash_attention   VMEM-tiled online-softmax attention (exported as
                    the MODULE for back-compat: bench.py and the
                    attention layers call ``flash_attention.
                    flash_attention(...)``)
  blockwise_softmax_cross_entropy / fused_mlm_head_loss
                    blockwise CE + fused MLM head (the [tokens, vocab]
                    logits never materialize; ``blockwise_ce``)
  fused_adam        one-pass m/v/param Adam update per parameter
  fused_layer_norm  one-pass LayerNorm fwd + bwd with saved residuals
  AutotuneCache / autotune_op
                    per-(op, shape, dtype, mesh, backend) block-size
                    sweep with a persistent, versioned JSON cache
                    (tools/autotune.py is the CLI), cost-model-pruned
                    to ``top_k`` measured candidates
  CostModel         analytic+fitted kernel cost model (costmodel):
                    ranks candidate configs, predicts configs for
                    never-swept shapes at trace time, prunes sweeps
"""
from . import flash_attention  # noqa: F401  (module — see docstring)
from .blockwise_ce import (  # noqa: F401
    blockwise_softmax_cross_entropy, fused_mlm_head_loss)
from .fused_adam import fused_adam  # noqa: F401  (function shadows its
#                                      submodule; internal callers import
#                                      from .fused_adam directly)
from .layer_norm import fused_layer_norm  # noqa: F401
from .autotune import (  # noqa: F401
    AutotuneCache, autotune_op, default_cache_path, CANDIDATES,
    fit_cost_model, banked_cache_path)
from .costmodel import CostModel  # noqa: F401
from ..pallas_dispatch import (  # noqa: F401
    PallasConfig, KernelChoice, cache_key, scope as pallas_scope,
    enabled as pallas_enabled, PALLAS_OPS, KERNEL_POLICIES)
