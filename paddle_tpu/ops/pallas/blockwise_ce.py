"""Blockwise softmax cross-entropy + fused MLM head (Pallas TPU).

The per-step hot spot after attention in every BASELINE LM config: the
``[tokens, vocab]`` logits tensor of the MLM/LM head. Two entries:

``blockwise_softmax_cross_entropy(logits, labels)``
    Streams existing logits block-by-block over the vocab axis with
    online logsumexp + gather-at-label accumulation, so the forward
    never materializes the ``[tokens, vocab]`` log-softmax/softmax
    intermediates XLA's lowering builds. Backward emits
    ``dlogits = (softmax - onehot) * dloss`` tile-by-tile straight from
    the ``lse`` residual (the input cotangent itself is unavoidable —
    it has the input's shape).

``fused_mlm_head_loss(hidden, weight, labels, bias=None)``
    The full fusion: computes ``hidden @ weight + bias`` INSIDE the
    kernel one ``(block_t, block_v)`` tile at a time, so the logits
    tensor never exists in HBM in forward OR backward — dhidden/dweight/
    dbias recompute each probability tile from the saved per-token
    logsumexp, flash-attention-style. Peak memory drops from
    O(tokens*vocab) to O(tokens*hidden + hidden*vocab).

Layout contract: 2-D problems — ``logits (T, V)``, ``hidden (T, D)``,
``weight (D, V)``, ``labels (T,) int``; callers collapse leading dims.
Per-token loss and residuals ride a sublane dim of 8 (Mosaic wants the
last-two block dims (8, 128)-aligned; row 0 is the real data — same
convention as flash_attention's lse). On CPU the kernels run in
interpret mode so tier-1 exercises the real kernel logic.

Entries return ``None`` when the shape cannot tile (caller falls back
to its XLA lowering) — the same size-guard contract as flash_attention.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .flash_attention import _dot_precision
from .costmodel import fit_blocks  # noqa: F401 - the kernels' tiling
# math lives in costmodel (pure, jax-free) so the cost model and the
# size guards can never disagree; re-exported here for the callers/
# tests that always imported it from this module
from .. import pallas_dispatch as pd

_NEG_INF = -1e30


def _label_zero_cot(labels):
    """Cotangent for an integer labels input: float0 zeros (the value
    jax.vjp expects for int primals; discarded by every caller)."""
    return np.zeros(np.shape(labels), dtype=jax.dtypes.float0)


def _rows8(x, dtype):
    """Broadcast a (T,) vector to (8, T) — the sublane-padded layout the
    per-token inputs/outputs ride through Mosaic."""
    return jnp.broadcast_to(jnp.asarray(x, dtype)[None, :],
                            (8,) + (x.shape[0],))


def _online_lse_update(s, m_ref, l_ref):
    """One blockwise logsumexp accumulation step over score tile `s`
    ((BT, BV) f32) against the running (max, sum) scratch pair."""
    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    l_new = corr * l_ref[:, :1] + jnp.sum(jnp.exp(s - m_new), axis=-1,
                                          keepdims=True)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)


def _label_hit(lab_ref, vj, block_t, block_v):
    """Bool (BT, BV) tile: does column j hold this row's label?"""
    lab = lab_ref[0].astype(jnp.int32)
    col = vj * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_t, block_v), 1)
    return col == lab[:, None]


def _finalize_loss(loss_ref, lse_ref, m_ref, l_ref, ll_ref):
    """Emit per-token loss = lse - logit[label] and the lse residual."""
    lse = m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))
    loss_ref[:] = jnp.broadcast_to((lse - ll_ref[:, 0])[None, :],
                                   loss_ref.shape).astype(loss_ref.dtype)
    lse_ref[:] = jnp.broadcast_to(lse[None, :],
                                  lse_ref.shape).astype(lse_ref.dtype)


def _p_ds(s, lse_ref, dl_ref, lab_ref, vj, block_t, block_v):
    """Probability tile p = exp(s - lse) and the logit cotangent
    ds = (p - onehot(label)) * dloss — the shared core of every
    backward kernel."""
    lse = lse_ref[0].astype(jnp.float32)
    dl = dl_ref[0].astype(jnp.float32)
    p = jnp.exp(s - lse[:, None])
    hit = _label_hit(lab_ref, vj, block_t, block_v)
    return (p - jnp.where(hit, 1.0, 0.0)) * dl[:, None]


# ---------------------------------------------------------------------------
# logits-level blockwise CE (the softmax_with_cross_entropy op lowering)
# ---------------------------------------------------------------------------

def _ce_fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, m_ref, l_ref, ll_ref,
                   *, block_t, block_v):
    vj = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        ll_ref[:] = jnp.zeros_like(ll_ref)

    s = x_ref[...].astype(jnp.float32)               # (BT, BV)
    _online_lse_update(s, m_ref, l_ref)
    hit = _label_hit(lab_ref, vj, block_t, block_v)
    ll_ref[:] = ll_ref[:] + jnp.broadcast_to(
        jnp.sum(jnp.where(hit, s, 0.0), axis=-1, keepdims=True),
        ll_ref.shape)

    @pl.when(vj == nv - 1)
    def _fin():
        _finalize_loss(loss_ref, lse_ref, m_ref, l_ref, ll_ref)


def _ce_bwd_kernel(x_ref, lab_ref, lse_ref, dl_ref, dx_ref,
                   *, block_t, block_v):
    vj = pl.program_id(1)
    s = x_ref[...].astype(jnp.float32)
    ds = _p_ds(s, lse_ref, dl_ref, lab_ref, vj, block_t, block_v)
    dx_ref[...] = ds.astype(dx_ref.dtype)


def _ce_call_fwd(logits, labels, block_t, block_v, interpret):
    t, v = logits.shape
    grid = (t // block_t, v // block_v)
    loss, lse = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, block_t=block_t,
                          block_v=block_v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda ti, vj: (ti, vj)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
        ],
        out_specs=[
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, t), jnp.float32),
            jax.ShapeDtypeStruct((8, t), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_t, 128), jnp.float32)
                        for _ in range(3)],
        interpret=interpret,
    )(logits, _rows8(labels, jnp.int32))
    return loss[0], lse[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _ce(logits, labels, block_t, block_v, interpret):
    loss, _ = _ce_call_fwd(logits, labels, block_t, block_v, interpret)
    return loss


def _ce_fwd(logits, labels, block_t, block_v, interpret):
    loss, lse = _ce_call_fwd(logits, labels, block_t, block_v, interpret)
    return loss, (logits, labels, lse)


def _ce_bwd(block_t, block_v, interpret, res, dloss):
    logits, labels, lse = res
    t, v = logits.shape
    dx = pl.pallas_call(
        functools.partial(_ce_bwd_kernel, block_t=block_t,
                          block_v=block_v),
        grid=(t // block_t, v // block_v),
        in_specs=[
            pl.BlockSpec((block_t, block_v), lambda ti, vj: (ti, vj)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
        ],
        out_specs=pl.BlockSpec((block_t, block_v),
                               lambda ti, vj: (ti, vj)),
        out_shape=jax.ShapeDtypeStruct((t, v), logits.dtype),
        interpret=interpret,
    )(logits, _rows8(labels, jnp.int32), _rows8(lse, jnp.float32),
      _rows8(dloss, jnp.float32))
    return dx, _label_zero_cot(labels)


_ce.defvjp(_ce_fwd, _ce_bwd)


def blockwise_softmax_cross_entropy(logits, labels, block_t=128,
                                    block_v=512, interpret=None):
    """Per-token softmax CE loss (f32, shape (T,)) streamed over vocab
    blocks of existing ``logits (T, V)``; ``labels (T,) int``. Returns
    None when the shape cannot tile — callers then take their XLA path.
    """
    if interpret is None:
        interpret = pd.default_interpret()
    t, v = logits.shape
    fit = fit_blocks(t, v, block_t, block_v, interpret)
    if fit is None:
        return None
    bt, bv = fit
    return _ce(jnp.asarray(logits), jnp.asarray(labels), bt, bv,
               bool(interpret))


# ---------------------------------------------------------------------------
# fused MLM head: hidden @ weight + bias -> CE, logits never in HBM
# ---------------------------------------------------------------------------

def _head_tile(h_ref, w_ref, b_ref, precision):
    """One (BT, BV) logits tile computed in-VMEM from the hidden and
    weight blocks — the materialization this kernel exists to avoid."""
    h = h_ref[...].astype(jnp.float32)               # (BT, D)
    w = w_ref[...].astype(jnp.float32)               # (D, BV)
    s = jax.lax.dot_general(h, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=precision)
    return h, s + b_ref[0][None, :].astype(jnp.float32)


def _head_fwd_kernel(h_ref, w_ref, b_ref, lab_ref, loss_ref, lse_ref,
                     m_ref, l_ref, ll_ref, *, block_t, block_v,
                     precision):
    vj = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        ll_ref[:] = jnp.zeros_like(ll_ref)

    _, s = _head_tile(h_ref, w_ref, b_ref, precision)
    _online_lse_update(s, m_ref, l_ref)
    hit = _label_hit(lab_ref, vj, block_t, block_v)
    ll_ref[:] = ll_ref[:] + jnp.broadcast_to(
        jnp.sum(jnp.where(hit, s, 0.0), axis=-1, keepdims=True),
        ll_ref.shape)

    @pl.when(vj == nv - 1)
    def _fin():
        _finalize_loss(loss_ref, lse_ref, m_ref, l_ref, ll_ref)


def _head_dh_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, dl_ref,
                    dh_ref, dh_acc, *, block_t, block_v, precision):
    vj = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vj == 0)
    def _init():
        dh_acc[:] = jnp.zeros_like(dh_acc)

    _, s = _head_tile(h_ref, w_ref, b_ref, precision)
    ds = _p_ds(s, lse_ref, dl_ref, lab_ref, vj, block_t, block_v)
    # dh += ds @ w^T
    dh_acc[:] = dh_acc[:] + jax.lax.dot_general(
        ds, w_ref[...].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)

    @pl.when(vj == nv - 1)
    def _fin():
        dh_ref[...] = dh_acc[:].astype(dh_ref.dtype)


def _head_dwb_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, dl_ref,
                     dw_ref, db_ref, dw_acc, db_acc, *, block_t, block_v,
                     precision):
    # grid (nv, nt): t innermost so dw/db accumulate per weight column
    vj = pl.program_id(0)
    ti = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(ti == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    h, s = _head_tile(h_ref, w_ref, b_ref, precision)
    ds = _p_ds(s, lse_ref, dl_ref, lab_ref, vj, block_t, block_v)
    # dw += h^T @ ds ; db += sum_t ds
    dw_acc[:] = dw_acc[:] + jax.lax.dot_general(
        h, ds, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)
    db_acc[:] = db_acc[:] + jnp.broadcast_to(
        jnp.sum(ds, axis=0, keepdims=True), db_acc.shape)

    @pl.when(ti == nt - 1)
    def _fin():
        dw_ref[...] = dw_acc[:].astype(dw_ref.dtype)
        db_ref[...] = db_acc[:].astype(db_ref.dtype)


def _head_call_fwd(hidden, weight, bias, labels, block_t, block_v,
                   interpret):
    t, d = hidden.shape
    v = weight.shape[1]
    prec = _dot_precision(hidden.dtype)
    loss, lse = pl.pallas_call(
        functools.partial(_head_fwd_kernel, block_t=block_t,
                          block_v=block_v, precision=prec),
        grid=(t // block_t, v // block_v),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda ti, vj: (ti, 0)),
            pl.BlockSpec((d, block_v), lambda ti, vj: (0, vj)),
            pl.BlockSpec((8, block_v), lambda ti, vj: (0, vj)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
        ],
        out_specs=[
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, t), jnp.float32),
            jax.ShapeDtypeStruct((8, t), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_t, 128), jnp.float32)
                        for _ in range(3)],
        interpret=interpret,
    )(hidden, weight, _rows8(bias, jnp.float32),
      _rows8(labels, jnp.int32))
    return loss[0], lse[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _head(hidden, weight, bias, labels, block_t, block_v, interpret):
    loss, _ = _head_call_fwd(hidden, weight, bias, labels, block_t,
                             block_v, interpret)
    return loss


def _head_fwd(hidden, weight, bias, labels, block_t, block_v, interpret):
    loss, lse = _head_call_fwd(hidden, weight, bias, labels, block_t,
                               block_v, interpret)
    return loss, (hidden, weight, bias, labels, lse)


def _head_bwd(block_t, block_v, interpret, res, dloss):
    hidden, weight, bias, labels, lse = res
    t, d = hidden.shape
    v = weight.shape[1]
    prec = _dot_precision(hidden.dtype)
    lab8 = _rows8(labels, jnp.int32)
    lse8 = _rows8(lse, jnp.float32)
    dl8 = _rows8(dloss, jnp.float32)
    bias8 = _rows8(bias, jnp.float32)
    common = dict(block_t=block_t, block_v=block_v, precision=prec)

    dh = pl.pallas_call(
        functools.partial(_head_dh_kernel, **common),
        grid=(t // block_t, v // block_v),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda ti, vj: (ti, 0)),
            pl.BlockSpec((d, block_v), lambda ti, vj: (0, vj)),
            pl.BlockSpec((8, block_v), lambda ti, vj: (0, vj)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
            pl.BlockSpec((8, block_t), lambda ti, vj: (0, ti)),
        ],
        out_specs=pl.BlockSpec((block_t, d), lambda ti, vj: (ti, 0)),
        out_shape=jax.ShapeDtypeStruct((t, d), hidden.dtype),
        scratch_shapes=[pltpu.VMEM((block_t, d), jnp.float32)],
        interpret=interpret,
    )(hidden, weight, bias8, lab8, lse8, dl8)

    dw, db8 = pl.pallas_call(
        functools.partial(_head_dwb_kernel, **common),
        grid=(v // block_v, t // block_t),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda vj, ti: (ti, 0)),
            pl.BlockSpec((d, block_v), lambda vj, ti: (0, vj)),
            pl.BlockSpec((8, block_v), lambda vj, ti: (0, vj)),
            pl.BlockSpec((8, block_t), lambda vj, ti: (0, ti)),
            pl.BlockSpec((8, block_t), lambda vj, ti: (0, ti)),
            pl.BlockSpec((8, block_t), lambda vj, ti: (0, ti)),
        ],
        out_specs=[
            pl.BlockSpec((d, block_v), lambda vj, ti: (0, vj)),
            pl.BlockSpec((8, block_v), lambda vj, ti: (0, vj)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, v), weight.dtype),
            jax.ShapeDtypeStruct((8, v), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((d, block_v), jnp.float32),
            pltpu.VMEM((8, block_v), jnp.float32),
        ],
        interpret=interpret,
    )(hidden, weight, bias8, lab8, lse8, dl8)

    return dh, dw, db8[0].astype(bias.dtype), _label_zero_cot(labels)


_head.defvjp(_head_fwd, _head_bwd)


def fused_mlm_head_loss(hidden, weight, labels, bias=None, block_t=128,
                        block_v=512, interpret=None):
    """Per-token CE loss of the LM/MLM head without ever materializing
    the ``[tokens, vocab]`` logits: ``hidden (T, D)``, ``weight (D, V)``,
    ``labels (T,) int``, optional ``bias (V,)``. Returns f32 ``(T,)``
    loss, or None when the shape cannot tile (caller computes the head
    through XLA instead). Differentiable wrt hidden/weight/bias; the
    backward recomputes each probability tile from the saved per-token
    logsumexp, so neither direction touches a (T, V) buffer."""
    if interpret is None:
        interpret = pd.default_interpret()
    t, d = hidden.shape
    v = weight.shape[1]
    fit = fit_blocks(t, v, block_t, block_v, interpret)
    if fit is None or d % 8:
        return None
    bt, bv = fit
    b = jnp.zeros((v,), jnp.float32) if bias is None else jnp.asarray(bias)
    return _head(jnp.asarray(hidden), jnp.asarray(weight), b,
                 jnp.asarray(labels), bt, bv, bool(interpret))
