"""Analytic + fitted kernel cost model — the sweep-pruning layer.

TVM (PAPERS.md) showed a cost model turning an exhaustive schedule
sweep into a handful of measured candidates; Tensor Processing
Primitives showed one selection layer picking the best primitive
implementation per call site. This module is both halves for the
Pallas kernel library:

  * **Analytic features** per (op, shape, block config): the tiling
    math of each kernel family (mirrored from the kernels' own size
    guards, kept pure so this module never imports jax), the grid
    size, the VMEM footprint of one tile, the total padded HBM
    traffic and the padding waste.
  * **Fitted model**: a least-squares fit of measured seconds over
    those features, using every ``(key, config, seconds)`` row banked
    in an :class:`~.autotune.AutotuneCache` (the sweeps now persist
    ALL candidate timings, not just the winner). One weight vector
    per (kernel family, backend, interpret) segment — interpret-mode
    wall time and Mosaic wall time are different physics and never
    share a fit.
  * **Ranking**: :meth:`CostModel.rank` orders a candidate list by
    predicted seconds (fitted when a segment has enough rows, the
    analytic proxy otherwise), :meth:`CostModel.top_k` prunes a sweep
    to K candidates, and :meth:`CostModel.predict_config` gives a
    NEVER-SWEPT shape a predicted config at trace time instead of the
    hardcoded kernel default.

Everything here is numpy + stdlib: ``pallas_dispatch`` (imported on
every trace) and ``framework/compiler`` consult the model without
dragging the kernel modules or jax.experimental.pallas in.
"""
import hashlib
import json
import math

import numpy as np

#: bumped whenever the feature map or the fit changes shape — part of
#: the executor compile-cache token (a stale jitted program must not
#: survive a model upgrade) and of the banked-cache check line.
MODEL_VERSION = 1

LANES = 128
#: per-core VMEM envelope the analytic proxy penalizes against (bytes).
#: Deliberately below the hardware's ~16 MiB: double-buffered pipelines
#: need headroom, and a config near the cliff is a bad bet anyway.
VMEM_BUDGET = 12 * 2 ** 20

#: assumed hidden size of the fused-MLM-head matmul when the call site
#: keys only (tokens, vocab): a shared per-family constant the fit
#: absorbs into its coefficients (interpret sweeps use tiny models)
HEAD_D = {"interpret": 16, "compiled": 768}

#: analytic proxy constants (seconds): per-grid-step overhead and
#: per-byte cost. Interpret mode executes the kernel body through the
#: Pallas interpreter, so its step cost dwarfs its byte cost; compiled
#: Mosaic is the opposite. Only the RANKING matters — the fitted model
#: replaces these the moment a sweep lands rows.
_STEP_S = {"interpret": 2e-4, "compiled": 2e-6}
_BYTE_S = {"interpret": 2e-9, "compiled": 1.2e-12}


def _mode(interpret):
    return "interpret" if interpret else "compiled"


# ---------------------------------------------------------------------------
# tiling feasibility — the kernels' size-guard math, kept pure
# ---------------------------------------------------------------------------

def fit_blocks(t, v, block_t, block_v, interpret):
    """(bt, bv) tile sizes for a (T, V) blockwise-CE/MLM-head problem,
    or None when it cannot tile: halve each block until it divides its
    axis; sub-8 tiles never tile, and compiled Mosaic needs the
    128-lane alignment (the loss/lse outputs put block_t on the lane
    dim). Interpret mode (CPU tests) accepts any divisible >= 8 tile.
    (Single source of truth — ``blockwise_ce.fit_blocks`` re-exports
    this.)"""
    bt, bv = min(block_t, t), min(block_v, v)
    while bt >= 1 and t % bt:
        bt //= 2
    while bv >= 1 and v % bv:
        bv //= 2
    if bt < 8 or bv < 8:
        return None
    if not interpret and (bt < 128 or bv < 128):
        return None
    return bt, bv


def _adam_tiles(n, block_rows, interpret):
    """(block_rows_eff, rows_padded) of the fused-adam lane layout for
    an n-element parameter, or None (too small / misaligned) — mirrors
    fused_adam's own guards."""
    rows = -(-int(n) // LANES)
    if rows < 8:
        return None
    rows = -(-rows // 8) * 8
    br = min(int(block_rows), rows)
    if not interpret and br % 8:
        return None
    rows_p = -(-rows // br) * br
    return br, rows_p


def _ln_tiles(rows, cols, block_rows, interpret):
    """(block_rows_eff, rows_padded) for fused_layer_norm, or None —
    mirrors its guards (compiled Mosaic wants cols 128-aligned and a
    128-multiple row block)."""
    rows, cols = int(rows), int(cols)
    if rows < 1 or cols < 8:
        return None
    br = min(int(block_rows), max(rows, 1))
    if not interpret:
        br = (br // 128) * 128
        if cols % 128 or br < 128:
            return None
    br = max(br, 1)
    rows_p = -(-rows // br) * br
    return br, rows_p


# ---------------------------------------------------------------------------
# analytic features
# ---------------------------------------------------------------------------

def features(op, shape, config, interpret):
    """Feature dict for one (op, shape, block config), or None when the
    config cannot tile the shape (mirrors the kernel size guards, so an
    infeasible candidate is pruned before anything is measured):

      grid        -- total grid steps across the op's fwd+bwd kernels
      tile_bytes  -- VMEM-resident bytes of one grid step
      total_bytes -- padded HBM traffic of one fwd+bwd step
      pad_waste   -- padded/real element ratio - 1
    """
    shape = tuple(int(d) for d in shape)
    cfg = dict(config or {})
    if op in ("softmax_with_cross_entropy", "fused_mlm_head_loss"):
        if len(shape) != 2:
            return None
        t, v = shape
        fit = fit_blocks(t, v, cfg.get("block_t", 128),
                         cfg.get("block_v", 512), interpret)
        if fit is None:
            return None
        bt, bv = fit
        grid1 = (t // bt) * (v // bv)
        if op == "softmax_with_cross_entropy":
            # fwd reads logits, bwd reads them again and writes dx
            return {"grid": 2 * grid1, "tile_bytes": 4 * bt * bv,
                    "total_bytes": 3 * 4 * t * v, "pad_waste": 0.0}
        d = HEAD_D[_mode(interpret)]
        if d % 8:
            return None
        # fwd + dh + dwb kernels; each tile holds the (bt, d) hidden
        # block, the (d, bv) weight block and the in-VMEM logits tile
        tile = 4 * (bt * d + d * bv + bt * bv)
        total = 3 * 4 * grid1 * (bt * d + d * bv)
        return {"grid": 3 * grid1, "tile_bytes": tile,
                "total_bytes": total, "pad_waste": 0.0}
    if op == "adam":
        n = int(np.prod(shape, dtype=np.int64))
        fit = _adam_tiles(n, cfg.get("block_rows", 256), interpret)
        if fit is None:
            return None
        br, rows_p = fit
        padded = rows_p * LANES
        # read p/g/m1/m2, write p/m1/m2 — 7 streams of the lane layout
        return {"grid": rows_p // br, "tile_bytes": 7 * 4 * br * LANES,
                "total_bytes": 7 * 4 * padded,
                "pad_waste": padded / float(max(n, 1)) - 1.0}
    if op == "layer_norm":
        if len(shape) != 2:
            return None
        r, c = shape
        fit = _ln_tiles(r, c, cfg.get("block_rows", 128), interpret)
        if fit is None:
            return None
        br, rows_p = fit
        padded = rows_p * c
        # fwd reads x writes y; bwd reads x/g writes dx (+ row residuals)
        return {"grid": 2 * (rows_p // br),
                "tile_bytes": 2 * 4 * br * c,
                "total_bytes": 5 * 4 * padded,
                "pad_waste": padded / float(max(r * c, 1)) - 1.0}
    return None


def _phi(f):
    """Fit basis: [1, grid, total_MB, tile_MB, waste_MB] — small, all
    physically monotonic, shared by every family (the per-family
    weight vectors give each its own physics)."""
    total_mb = f["total_bytes"] / 1e6
    return np.array([1.0, float(f["grid"]), total_mb,
                     f["tile_bytes"] / 1e6, f["pad_waste"] * total_mb],
                    dtype=np.float64)


def analytic_seconds(f, interpret):
    """The no-data proxy: bytes over bandwidth + per-grid-step
    overhead, with a soft cliff past the VMEM budget. Replaced by the
    fitted model as soon as a segment has rows; until then only the
    RANKING it induces matters."""
    mode = _mode(interpret)
    t = f["total_bytes"] * _BYTE_S[mode] + f["grid"] * _STEP_S[mode]
    if f["tile_bytes"] > VMEM_BUDGET:
        t *= 4.0 * f["tile_bytes"] / VMEM_BUDGET
    return t


# ---------------------------------------------------------------------------
# cache-key / tag plumbing shared with autotune
# ---------------------------------------------------------------------------

def config_tag(config):
    """The sweep's per-candidate tag: ``"block_t=8,block_v=64"``."""
    return ",".join("%s=%s" % kv for kv in sorted((config or {}).items()))


def parse_tag(tag):
    """Inverse of :func:`config_tag` (int-valued block knobs)."""
    cfg = {}
    for item in str(tag).split(","):
        if not item:
            continue
        k, _, v = item.partition("=")
        try:
            cfg[k] = int(v)
        except ValueError:
            cfg[k] = v
    return cfg


def parse_key(key):
    """Split a ``pallas_dispatch.cache_key`` back into
    ``(op, shape, dtype, axes, backend)`` — how the fit recovers the
    problem geometry from banked rows. Returns None for keys this
    model version cannot parse (forward compat: unknown keys are
    skipped, never fatal)."""
    parts = str(key).split("|")
    if len(parts) != 5:
        return None
    op, dims, dtype, axes, backend = parts
    try:
        shape = tuple(int(d) for d in dims.split("x"))
    except ValueError:
        return None
    return op, shape, dtype, axes, backend


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------

#: fewer measured rows than this on a key and "best in top-k" is moot —
#: shared by tunecheck, bench_micro and the ranking-quality tests
MIN_RANK_ROWS = 4


def measured_best_in_topk(entries, model=None, k=3,
                          min_rows=MIN_RANK_ROWS):
    """Ranking-quality census over banked entries: ``(hits, judged)``
    where judged counts keys with >= ``min_rows`` numeric per-candidate
    rows and hits counts those whose measured-best config lands in the
    model's top-``k`` ranking of exactly those rows' configs. The ONE
    implementation behind the tunecheck gate, the bench_micro budget
    and the test batteries — they must all judge the same population.
    ``model`` defaults to an in-sample fit over ``entries``."""
    data = entries.load() if hasattr(entries, "load") else dict(entries)
    if model is None:
        model = CostModel().fit_cache(data)
    hits = judged = 0
    for key, entry in data.items():
        parsed = parse_key(key)
        if parsed is None or not isinstance(entry, dict):
            continue
        results = {t: s for t, s in (entry.get("results") or {}).items()
                   if isinstance(s, (int, float))}
        if len(results) < min_rows:
            continue
        op, shape, _dtype, _axes, backend = parsed
        ranked = model.rank(op, shape,
                            [parse_tag(t) for t in results],
                            backend=backend,
                            interpret=bool(entry.get("interpret")))
        topk = [config_tag(c) for c, _s, _src in ranked[:k]]
        judged += 1
        hits += min(results, key=results.get) in topk
    return hits, judged


class CostModel(object):
    """Per-family analytic+fitted cost model.

    ``candidates`` maps op -> candidate config list: the space
    :meth:`rank`/:meth:`predict_config` search when the caller does not
    hand one in (normally ``autotune.candidates_for``'s grids). Rows
    are segmented by (op, backend, interpret) so chip measurements
    never contaminate interpreter predictions.
    """

    def __init__(self, candidates=None):
        self.candidates = {op: [dict(c) for c in cfgs]
                           for op, cfgs in (candidates or {}).items()}
        self._rows = {}          # segment -> [(phi, seconds)]
        self._theta = {}         # segment -> weight vector
        self._n_rows = 0
        self._src = None         # (path, entry count) of the last fit

    # -- rows ----------------------------------------------------------
    def add_row(self, op, shape, config, seconds, backend=None,
                interpret=False):
        """One measured (op, shape, config) -> seconds observation."""
        f = features(op, shape, config, interpret)
        if f is None or seconds is None:
            return False
        seg = (op, backend or "-", bool(interpret))
        self._rows.setdefault(seg, []).append(
            (_phi(f), float(seconds)))
        self._n_rows += 1
        self._theta.pop(seg, None)     # refit lazily
        return True

    def fit_cache(self, cache):
        """Ingest every measured row an AutotuneCache banked: each
        entry's per-candidate ``results`` (all sweep timings) plus the
        winner's own ``pallas_s``. Unparseable keys/tags are skipped —
        a hand-edited cache degrades the fit, never the load."""
        data = cache.load() if hasattr(cache, "load") else dict(cache)
        for key, entry in data.items():
            parsed = parse_key(key)
            if parsed is None or not isinstance(entry, dict):
                continue
            op, shape, _dtype, _axes, backend = parsed
            interp = bool(entry.get("interpret"))
            results = entry.get("results") or {}
            seen = False
            for tag, sec in results.items():
                if isinstance(sec, dict):      # rich summary row
                    sec = sec.get("measured_s")
                if not isinstance(sec, (int, float)):
                    continue
                seen |= self.add_row(op, shape, parse_tag(tag), sec,
                                     backend=backend, interpret=interp)
            if not seen and entry.get("impl") == "pallas" and \
                    entry.get("config") and entry.get("pallas_s"):
                self.add_row(op, shape, entry["config"],
                             entry["pallas_s"], backend=backend,
                             interpret=interp)
        self._src = (getattr(cache, "path", None), len(data))
        return self

    # -- fit / predict -------------------------------------------------
    def _weights(self, seg):
        """Per-segment weight vector over log-seconds (predictions are
        ``exp(phi . theta)`` — always positive, so one ranking never
        mixes fitted and analytic scales), or None below the row floor.
        """
        if seg in self._theta:
            return self._theta[seg]
        rows = self._rows.get(seg)
        theta = None
        if rows and len(rows) >= 6:    # > basis size: never underdetermined
            A = np.stack([r[0] for r in rows])
            b = np.log(np.maximum([r[1] for r in rows], 1e-12))
            # column scaling keeps lstsq conditioned across the MB/grid
            # magnitude spread
            scale = np.maximum(np.abs(A).max(axis=0), 1e-12)
            sol = np.linalg.lstsq(A / scale, b, rcond=None)[0]
            theta = sol / scale
        self._theta[seg] = theta
        return theta

    #: reported predicted seconds stay within this factor of the
    #: analytic proxy: a fit extrapolated far outside its banked shape
    #: range keeps its RANKING (the raw score orders candidates) but
    #: must not export an absurd magnitude to spans/summaries
    REPORT_ENVELOPE = 50.0

    def _predict_raw(self, op, shape, config, backend=None,
                     interpret=False):
        """(reported_s, raw_score, source) or None when infeasible —
        raw_score is the pure fit (what rankings sort by), reported_s
        the envelope-clamped value callers may show humans."""
        f = features(op, shape, config, interpret)
        if f is None:
            return None
        ana = analytic_seconds(f, interpret)
        theta = self._weights((op, backend or "-", bool(interpret)))
        if theta is not None:
            logt = float(np.dot(_phi(f), theta))
            if math.isfinite(logt):
                raw = math.exp(min(max(logt, -46.0), 46.0))
                env = self.REPORT_ENVELOPE
                return min(max(raw, ana / env), ana * env), raw, "fitted"
        return ana, ana, "analytic"

    def predict(self, op, shape, config, backend=None, interpret=False):
        """(seconds, source) for one candidate, or (None, None) when it
        cannot tile. source is "fitted" | "analytic"."""
        out = self._predict_raw(op, shape, config, backend=backend,
                                interpret=interpret)
        if out is None:
            return None, None
        return out[0], out[2]

    def rank(self, op, shape, candidates=None, backend=None,
             interpret=False):
        """Candidates ordered by predicted seconds (infeasible ones
        dropped): list of ``(config, predicted_s, source)``. The order
        comes from the raw fit scores; the listed seconds are the
        envelope-clamped reported values."""
        if candidates is None:
            candidates = self.candidates.get(op, ())
        scored = []
        for cfg in candidates:
            out = self._predict_raw(op, shape, cfg, backend=backend,
                                    interpret=interpret)
            if out is not None:
                scored.append((dict(cfg), out[0], out[2], out[1]))
        scored.sort(key=lambda x: x[3])
        return [(c, t, src) for c, t, src, _raw in scored]

    def top_k(self, op, shape, candidates=None, k=3, backend=None,
              interpret=False):
        """The pruned sweep: the K best-predicted feasible candidates
        (the whole point — autotune measures these instead of the full
        space)."""
        return self.rank(op, shape, candidates, backend=backend,
                         interpret=interpret)[:max(1, int(k))]

    def predict_config(self, op, shape, backend=None, interpret=False):
        """Best predicted config for a NEVER-SWEPT shape (trace-time
        cache miss), or None when nothing in the candidate space tiles
        it — the caller then keeps the kernel-default fallback."""
        best = self.top_k(op, shape, k=1, backend=backend,
                          interpret=interpret)
        if not best:
            return None
        cfg, sec, src = best[0]
        return {"config": cfg, "predicted_s": sec, "source": src}

    # -- identity ------------------------------------------------------
    def rows_total(self):
        return self._n_rows

    def fingerprint(self):
        """Stable identity of (model version, candidate space, fitted
        rows) — joins the executor compile-cache token so flipping the
        model or re-banking a cache re-lowers."""
        h = hashlib.sha1()
        h.update(b"v%d|" % MODEL_VERSION)
        h.update(json.dumps(self.candidates, sort_keys=True,
                            default=str).encode())
        for seg in sorted(self._rows):
            rows = self._rows[seg]
            h.update(("%s|%d|" % (seg, len(rows))).encode())
            h.update(np.array([r[1] for r in rows]).tobytes())
        return h.hexdigest()[:16]

    def stats(self):
        segs = sorted(self._rows)
        return {"model_version": MODEL_VERSION,
                "rows": self._n_rows,
                "segments": ["%s@%s%s" % (op, be, "/interp" if it
                                          else "")
                             for op, be, it in segs],
                "fitted": ["%s@%s%s" % (op, be, "/interp" if it else "")
                           for op, be, it in segs
                           if self._weights((op, be, it)) is not None],
                "fingerprint": self.fingerprint()}
