"""Pallas TPU flash attention — placeholder raising until the kernel lands
later this round; callers fall back to the fused XLA path."""


def flash_attention(q, k, v, mask=None, scale=1.0, causal=False):
    raise NotImplementedError("pallas flash attention not built yet")
